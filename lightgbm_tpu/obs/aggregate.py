"""Fleet aggregation: pod-level metrics merged at iteration boundaries.

Per-process registries (obs/registry.py) answer "what did MY rank do";
this module answers "what did the POD do" — the per-rank visibility the
reference's `Network::Allreduce` stack never had. At each iteration
boundary every rank packs a small float32 payload (iteration wall,
cumulative collective bytes/calls, fetch p99, live HBM bytes) and
`network.fleet_allgather` merges it — piggybacking on the SAME
allgather `straggler_stats` already paid for the `coll.host_skew`
gauge, so turning the fleet plane on adds zero extra blocking syncs
per iteration (tracer-verified in tests/test_fleet_obs.py).

Rank 0's JSONL records gain a `fleet` object (schema minor 11):
iter-time min/mean/max over ranks, the skew trend (EMA-debiased
direction — a growing skew is a straggler developing, a spike is a
transient), per-rank collective-byte deltas, and a PERSISTENT per-rank
straggler table that generalizes the single `coll.slowest_rank` gauge
(which is kept — the watchdog and schema minors ≤10 read it): how
often each rank was slowest, its EMA iteration time, cumulative bytes.

Single-process runs skip the collective entirely and still emit a
1-rank fleet view, so the record shape is testable on the CPU mesh.
There is one process-global active aggregator (`activate_aggregator` /
`active_aggregator`) so the /statusz endpoint can render the live
table without threading a handle through the engine.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .registry import MetricsRegistry

# payload slot order — slot 0 MUST stay the iteration wall so the skew
# math is byte-for-byte what straggler_stats computed before the widen
PAYLOAD_FIELDS = ("iter_s", "coll_bytes", "coll_calls",
                  "fetch_p99_ms", "mem_bytes")

_EMA_ALPHA = 0.3    # per-rank iter-time EMA + skew-trend smoothing


class FleetAggregator:
    """Builds per-rank payloads and folds gathered payloads into the
    pod view. All state is host-side and O(nranks)."""

    def __init__(self) -> None:
        self._prev: Optional[np.ndarray] = None   # cumulative snapshot
        self._skew_ema: Optional[float] = None
        # rank -> {"iter_ema_s", "slowest_count", "coll_bytes"}
        self._table: Dict[int, Dict[str, float]] = {}
        self.last_fleet: Optional[Dict[str, Any]] = None

    # -- payload (every rank) -------------------------------------------
    def local_payload(self, reg: MetricsRegistry,
                      iter_s: float) -> List[float]:
        coll_bytes = 0.0
        coll_calls = 0.0
        for key, v in reg.counters.items():
            if key.startswith("collective.") and key.endswith(".bytes"):
                coll_bytes += v
            elif key.startswith("collective.") and key.endswith(".calls"):
                coll_calls += v
        fetch_p99 = reg.latency_percentile("lat.fetch.device_get", 0.99)
        mem = reg.gauges.get("mem.live_bytes", 0.0)
        return [float(iter_s), coll_bytes, coll_calls,
                float(fetch_p99 or 0.0), float(mem)]

    # -- merge (rank 0; every rank on single-process) ---------------------
    def update(self, gathered: np.ndarray) -> Dict[str, Any]:
        """Fold one (nranks, len(PAYLOAD_FIELDS)) gather into the pod
        view and return the `fleet` record object."""
        gathered = np.asarray(gathered, dtype=np.float64)
        nranks = gathered.shape[0]
        iters = gathered[:, 0]
        mean = float(iters.mean())
        skew = (float((iters.max() - iters.min()) / mean)
                if mean > 0 else 0.0)
        if self._skew_ema is None:
            trend = 0.0
            self._skew_ema = skew
        else:
            trend = skew - self._skew_ema
            self._skew_ema += _EMA_ALPHA * (skew - self._skew_ema)
        slowest = int(iters.argmax())
        deltas = (gathered - self._prev if self._prev is not None
                  and self._prev.shape == gathered.shape
                  else np.zeros_like(gathered))
        self._prev = gathered.copy()

        per_rank = []
        for r in range(nranks):
            row = self._table.setdefault(
                r, {"iter_ema_s": float(iters[r]),
                    "slowest_count": 0, "coll_bytes": 0.0})
            row["iter_ema_s"] += _EMA_ALPHA * (float(iters[r])
                                               - row["iter_ema_s"])
            if r == slowest and nranks > 1:
                row["slowest_count"] += 1
            row["coll_bytes"] = float(gathered[r, 1])
            per_rank.append({
                "rank": r,
                "iter_s": round(float(iters[r]), 6),
                "iter_ema_s": round(row["iter_ema_s"], 6),
                "slowest_count": int(row["slowest_count"]),
                "coll_bytes": int(gathered[r, 1]),
                "coll_bytes_delta": int(max(0.0, deltas[r, 1])),
                "fetch_p99_ms": round(float(gathered[r, 3]), 6),
                "mem_bytes": int(gathered[r, 4]),
            })
        fleet = {
            "ranks": nranks,
            "iter_min_s": round(float(iters.min()), 6),
            "iter_mean_s": round(mean, 6),
            "iter_max_s": round(float(iters.max()), 6),
            "skew": round(skew, 6),
            "skew_trend": round(trend, 6),
            "slowest_rank": slowest,
            "per_rank": per_rank,
        }
        self.last_fleet = fleet
        return fleet

    def step(self, reg: MetricsRegistry, iter_s: float,
             _gather=None) -> Optional[Dict[str, Any]]:
        """One iteration boundary: pack, allgather (the piggybacked
        sync — the only one this plane pays), merge, and set the skew /
        slowest-rank gauges `straggler_stats` used to own. Returns the
        fleet object (all ranks hold it; only rank 0's sink writes
        it)."""
        from ..network import fleet_allgather
        payload = self.local_payload(reg, iter_s)
        gathered = fleet_allgather(payload, _gather=_gather)
        if gathered is None:        # single-process: local-only view
            gathered = np.asarray([payload], dtype=np.float64)
        fleet = self.update(gathered)
        reg.set_gauge("coll.host_skew", fleet["skew"])
        reg.set_gauge("coll.slowest_rank", fleet["slowest_rank"])
        return fleet

    def table(self) -> List[Dict[str, Any]]:
        """Live straggler table for /statusz (copy — handler threads
        must not alias mutable state)."""
        fleet = self.last_fleet
        return [dict(row) for row in fleet["per_rank"]] if fleet else []


# -- process-global active aggregator ------------------------------------
_ACTIVE: Optional[FleetAggregator] = None


def activate_aggregator(agg: FleetAggregator) -> FleetAggregator:
    global _ACTIVE
    _ACTIVE = agg
    return agg


def deactivate_aggregator(agg: Optional[FleetAggregator] = None) -> None:
    global _ACTIVE
    if agg is None or _ACTIVE is agg:
        _ACTIVE = None


def active_aggregator() -> Optional[FleetAggregator]:
    return _ACTIVE
