"""Anomaly-triggered flight recorder: dump the evidence, atomically.

When something goes wrong mid-run — the watchdog trips, a numeric
sentinel trips, or an iteration blows its latency SLO — the state that
explains it (the trace ring, the registry, the fleet table, every
thread's stack) is about to be lost to the crash handler or the next
iteration. This module freezes it: one timestamped bundle directory
under `flight_dir`, written tmp-dir-then-rename so a reader (or a
SIGKILL) can never observe a torn bundle.

Triggers (docs/ROBUSTNESS.md "Self-healing" matrix):

- **watchdog** — `robust/watchdog.py` `_trip` calls the active
  recorder right after building its diagnosis,
- **sentinel** — `robust/sentinel.py` `_judge` calls it on a trip,
- **slo** — `observe_iteration` fires when an iteration's wall time
  exceeds `flight_slo_factor` × the rolling p50 (window 64, armed
  after 8 samples; factor 0 disables). Breaches always count
  (`slo.breaches`), dumps rate-limit under a cooldown so a persistent
  stall costs one bundle, not one per iteration.

Bundle contents: `manifest.json` (trigger, iteration, config text +
trace_signature), `trace.json` (the last-N-iteration ring as Perfetto
JSON — loads in ui.perfetto.dev), `registry.json` (counters / gauges /
phase times / last record), `fleet.json` (per-rank straggler table,
when the fleet plane is on), `stacks.txt` (all thread stacks).
Summarize one with `python -m lightgbm_tpu trace-report --flight DIR`.

File writes route through the `sink.write` fault seam, so the same
chaos plans that prove the JSONL sink's failure behaviour prove bundle
atomicity: an injected ENOSPC mid-bundle leaves NO bundle (the tmp dir
is removed), never a partial one. Counters: `flight.dumps`,
`flight.<trigger>`, `flight.failed`, `slo.breaches`.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, Optional

from ..utils import log
from . import registry as _registry
from . import trace as _trace

_SLO_WINDOW = 64       # rolling iteration-wall samples for the p50
_SLO_WARMUP = 8        # samples before the SLO trigger arms
_COOLDOWN_S = 30.0     # min seconds between bundles
_KEEP_BUNDLES = 8      # newest bundles retained in flight_dir


class FlightRecorder:
    """All mutable state is guarded by `_lock`: dumps arrive from the
    training thread (SLO), the watchdog thread (trips) and sentinel
    resolution, concurrently."""

    def __init__(self, flight_dir: str, slo_factor: float = 0.0,
                 context: Optional[Dict[str, Any]] = None,
                 cooldown_s: float = _COOLDOWN_S,
                 clock=time.monotonic) -> None:
        self.flight_dir = flight_dir
        self.slo_factor = float(slo_factor)
        self.context = dict(context or {})
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._iter_walls: deque = deque(maxlen=_SLO_WINDOW)
        self._last_dump_t: Optional[float] = None
        self._seq = 0
        self.dumps = 0
        self.last_bundle: Optional[str] = None

    # -- SLO trigger ------------------------------------------------------
    def observe_iteration(self, iteration: int, wall_s: float) -> None:
        """Feed one iteration wall time; may fire the `slo` trigger."""
        if wall_s <= 0:
            return
        with self._lock:
            samples = sorted(self._iter_walls)
            self._iter_walls.append(wall_s)
        if (self.slo_factor <= 0 or len(samples) < _SLO_WARMUP):
            return
        p50 = samples[len(samples) // 2]
        if wall_s <= self.slo_factor * p50:
            return
        reg = _registry.active()
        if reg is not None:
            reg.inc("slo.breaches")
        self.dump("slo", {"iteration": int(iteration),
                          "wall_s": round(wall_s, 6),
                          "rolling_p50_s": round(p50, 6),
                          "slo_factor": self.slo_factor})

    # -- bundle writer ----------------------------------------------------
    def dump(self, trigger: str, info: Optional[Dict[str, Any]] = None
             ) -> Optional[str]:
        """Write one bundle; returns its path, or None when skipped
        (cooldown) or failed (fault/IO — never raises: the recorder
        must not turn an anomaly into a crash)."""
        now = self._clock()
        with self._lock:
            if (self._last_dump_t is not None
                    and now - self._last_dump_t < self.cooldown_s):
                return None
            self._last_dump_t = now
            self._seq += 1
            seq = self._seq
        stamp = time.strftime("%Y%m%d_%H%M%S")
        name = f"flight_{stamp}_{seq:03d}_{trigger}"
        final = os.path.join(self.flight_dir, name)
        tmp = os.path.join(self.flight_dir, f".tmp_{name}")
        reg = _registry.active()
        try:
            os.makedirs(tmp, exist_ok=True)
            self._write_bundle(tmp, trigger, info, reg)
            os.rename(tmp, final)    # atomic: readers never see a torn dir
        except OSError as exc:
            shutil.rmtree(tmp, ignore_errors=True)
            if reg is not None:
                reg.inc("flight.failed")
            log.warning("flight recorder: bundle %s failed: %s", name, exc)
            return None
        with self._lock:
            self.dumps += 1
            self.last_bundle = final
        if reg is not None:
            reg.inc("flight.dumps")
            reg.inc(f"flight.{trigger}")
        log.warning("flight recorder: %s trigger -> %s", trigger, final)
        self._prune()
        return final

    def _write_bundle(self, tmp: str, trigger: str,
                      info: Optional[Dict[str, Any]],
                      reg: Optional[_registry.MetricsRegistry]) -> None:
        manifest: Dict[str, Any] = {
            "trigger": trigger,
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "info": info or {},
        }
        manifest.update(self.context)
        tr = _trace.active_tracer()
        if tr is not None:
            self._write(os.path.join(tmp, "trace.json"),
                        json.dumps(tr.to_perfetto()))
            manifest["trace_events"] = len(tr)
        if reg is not None:
            snap = {
                "counters": dict(reg.counters),
                "gauges": dict(reg.gauges),
                "phases": dict(reg.times),
                "last_record": reg.last_record,
                "lat": {k: h.snapshot()
                        for k, h in reg.latency_histograms().items()},
            }
            self._write(os.path.join(tmp, "registry.json"),
                        json.dumps(snap, default=str))
        try:
            from .aggregate import active_aggregator
            agg = active_aggregator()
        except Exception:
            agg = None
        if agg is not None and agg.last_fleet is not None:
            self._write(os.path.join(tmp, "fleet.json"),
                        json.dumps(agg.last_fleet))
        self._write(os.path.join(tmp, "stacks.txt"), _thread_stacks())
        self._write(os.path.join(tmp, "manifest.json"),
                    json.dumps(manifest, indent=1, default=str))

    @staticmethod
    def _write(path: str, text: str) -> None:
        # same seam as the JSONL sink (lazy import mirrors sink.write —
        # obs must stay importable without the robust package): one
        # chaos plan proves both writers' failure behaviour
        from ..robust.faultinject import check_fault
        check_fault("sink.write")
        with open(path, "w") as fh:
            fh.write(text)

    def _prune(self) -> None:
        try:
            bundles = sorted(
                d for d in os.listdir(self.flight_dir)
                if d.startswith("flight_")
                and os.path.isdir(os.path.join(self.flight_dir, d)))
            for stale in bundles[:-_KEEP_BUNDLES]:
                shutil.rmtree(os.path.join(self.flight_dir, stale),
                              ignore_errors=True)
        except OSError:
            pass


def _thread_stacks() -> str:
    """Every thread's stack — the same evidence the watchdog logs at
    trip time, preserved in the bundle."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


# -- process-global active recorder ---------------------------------------
_ACTIVE: Optional[FlightRecorder] = None


def activate_flight(fr: FlightRecorder) -> FlightRecorder:
    global _ACTIVE
    _ACTIVE = fr
    return fr


def deactivate_flight(fr: Optional[FlightRecorder] = None) -> None:
    global _ACTIVE
    if fr is None or _ACTIVE is fr:
        _ACTIVE = None


def active_flight() -> Optional[FlightRecorder]:
    return _ACTIVE
