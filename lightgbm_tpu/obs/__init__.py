"""Unified training telemetry (docs/OBSERVABILITY.md).

Layers:

- `MetricsRegistry` (obs/registry.py): counters / gauges / histograms
  + per-iteration snapshots; one process-global active registry that
  instrumentation reads with a single `is None` check.
- `span` / `instrument_kernel` / `step_span` (obs/spans.py): scopes
  that feed the utils/timer.py table, the registry,
  jax.profiler trace annotations, and the runtime tracer at once.
- `Tracer` (obs/trace.py): bounded ring buffer of phase/sync/memory/
  collective events, exported as a Perfetto-loadable trace.json;
  `obs/report.py` summarizes one (also `python -m lightgbm_tpu
  trace-report`).
- `JsonlSink` + schema validators (obs/sink.py).
- The pod-scale plane (schema minor 11): `FleetAggregator`
  (obs/aggregate.py) merges per-rank registry deltas over the
  straggler allgather; `ObsServer` (obs/httpd.py) serves /metrics
  /healthz /statusz on a localhost daemon thread; `FlightRecorder`
  (obs/flight.py) dumps an atomic evidence bundle on watchdog /
  sentinel / SLO triggers.
- `TelemetrySession` (below): ties registry + sink + profiler + tracer
  + fleet + endpoint + flight recorder to the engine loop, configured
  from `Config` (`metrics_file`, `profile_dir`, `trace_file`,
  `metrics_interval`, `obs_port`, `flight_dir`, `flight_slo_factor`).

A session is **lightweight** when only the live plane is on
(`obs_port` / `flight_dir`, no metrics/profile/trace file): the engine
keeps the pipelined dispatch-ahead loop — no per-iteration stream
sync, no device stat fetches — and the one blocking sync the plane is
allowed per iteration is the fleet allgather it piggybacks on.

Everything is off by default: with no active registry, no timer, no
tracer, and no profile dir, the instrumentation fast paths reduce to a
global load per call.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from .aggregate import (FleetAggregator, activate_aggregator,
                        active_aggregator, deactivate_aggregator)
from .flight import (FlightRecorder, activate_flight, active_flight,
                     deactivate_flight)
from .registry import (LatencyHistogram, MetricsRegistry, activate, active,
                       deactivate)
from .sink import (SCHEMA_MINOR, SCHEMA_VERSION, JsonlSink, read_jsonl,
                   validate_bench_record, validate_record)
from .spans import (instrument_kernel, span, start_profiler, step_span,
                    stop_profiler)
from .trace import (Tracer, activate_tracer, active_tracer,
                    deactivate_tracer, install_sync_tracing,
                    live_array_bytes, merge_trace_events, merge_trace_files,
                    sync_attribution, uninstall_sync_tracing)

__all__ = [
    "MetricsRegistry", "LatencyHistogram", "activate", "active",
    "deactivate",
    "SCHEMA_VERSION", "SCHEMA_MINOR", "JsonlSink", "read_jsonl",
    "validate_record",
    "validate_bench_record", "span", "step_span", "instrument_kernel",
    "start_profiler", "stop_profiler", "TelemetrySession",
    "Tracer", "activate_tracer", "active_tracer", "deactivate_tracer",
    "install_sync_tracing", "uninstall_sync_tracing", "live_array_bytes",
    "sync_attribution", "merge_trace_events", "merge_trace_files",
    "FleetAggregator", "activate_aggregator", "active_aggregator",
    "deactivate_aggregator",
    "FlightRecorder", "activate_flight", "active_flight",
    "deactivate_flight",
]


class TelemetrySession:
    """Per-train() telemetry: activates a registry, opens the JSONL
    sink, optionally starts a jax.profiler trace and/or the runtime
    tracer, and snapshots every iteration. Built by the engine when the
    Config enables any of it; `from_config` returns None otherwise so
    the disabled path costs nothing."""

    def __init__(self, metrics_file: str = "", profile_dir: str = "",
                 interval: int = 1,
                 registry: Optional[MetricsRegistry] = None,
                 trace_file: str = "",
                 trace_capacity: int = 262144,
                 obs_port: int = 0,
                 flight_dir: str = "",
                 flight_slo_factor: float = 0.0,
                 fleet: bool = True,
                 flight_context: Optional[Dict[str, Any]] = None) -> None:
        # an already-active registry (bench.py activates one for the
        # whole process) keeps accumulating — the session must not
        # shadow it with a fresh one and silently fork the counters
        if registry is None:
            registry = active()
        self.registry = registry if registry is not None else MetricsRegistry()
        self.sink = JsonlSink(metrics_file) if metrics_file else None
        self.interval = max(1, int(interval))
        self.profile_dir = profile_dir
        self.trace_file = trace_file
        self.tracer = Tracer(trace_capacity) if trace_file else None
        # lightweight = live plane only: the engine keeps the pipelined
        # loop (no stream sync, no device stat fetch per iteration)
        self.lightweight = not (metrics_file or profile_dir or trace_file)
        self.obs_port = int(obs_port or 0)
        self.server = None          # ObsServer, built in start()
        self.fleet_agg = FleetAggregator() if fleet else None
        self.flight = (FlightRecorder(flight_dir, flight_slo_factor,
                                      context=flight_context)
                       if flight_dir else None)
        self._step = None
        self._started = False
        self._prev_registry: Optional[MetricsRegistry] = None
        self._iter_t0_ns = 0
        self._mem_peak = 0
        self._fleet_last: Optional[Dict[str, Any]] = None

    @classmethod
    def from_config(cls, cfg: Any) -> Optional["TelemetrySession"]:
        metrics_file = getattr(cfg, "metrics_file", "") or ""
        profile_dir = getattr(cfg, "profile_dir", "") or ""
        trace_file = getattr(cfg, "trace_file", "") or ""
        obs_port = int(getattr(cfg, "obs_port", 0) or 0)
        flight_dir = getattr(cfg, "flight_dir", "") or ""
        if not metrics_file and not profile_dir and not trace_file \
                and obs_port <= 0 and not flight_dir:
            return None
        flight_context: Optional[Dict[str, Any]] = None
        if flight_dir:
            flight_context = {}
            try:
                flight_context["config"] = cfg.to_params_string()
            except Exception:
                pass
            try:
                from ..compile.signature import _digest, config_signature
                flight_context["trace_signature"] = _digest(
                    config_signature(cfg))
            except Exception:
                pass
        return cls(metrics_file, profile_dir,
                   getattr(cfg, "metrics_interval", 1),
                   trace_file=trace_file,
                   trace_capacity=getattr(cfg, "trace_buffer_events",
                                          262144),
                   obs_port=obs_port,
                   flight_dir=flight_dir,
                   flight_slo_factor=getattr(cfg, "flight_slo_factor", 0.0),
                   fleet=bool(getattr(cfg, "fleet_metrics", True)),
                   flight_context=flight_context)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._started:
            return
        self._prev_registry = active()
        activate(self.registry)
        if self.profile_dir:
            start_profiler(self.profile_dir)
        if self.tracer is not None:
            activate_tracer(self.tracer)
        # the sync patch feeds lat.fetch.* histograms even without a
        # tracer (schema minor 11), so every session installs it
        install_sync_tracing()
        if self.fleet_agg is not None:
            activate_aggregator(self.fleet_agg)
        if self.flight is not None:
            activate_flight(self.flight)
        if self.obs_port > 0:
            from .httpd import ObsServer   # imported only when on
            self.server = ObsServer(self.obs_port)
            try:
                self.server.start()
            except OSError as exc:
                from ..utils import log
                log.warning("obs_port=%d: endpoint failed to start (%s); "
                            "training continues without it",
                            self.obs_port, exc)
                self.server = None
        self._started = True

    def begin_iteration(self, iteration: int) -> None:
        self._exit_step()
        self._step = step_span(iteration)
        self._step.__enter__()
        if self.tracer is not None:
            self.tracer.iteration = int(iteration)
            self._iter_t0_ns = self.tracer.now_ns()
        self.registry.begin_iteration(iteration)

    @property
    def sink_disabled(self) -> bool:
        return self.sink is not None and self.sink.disabled

    def record_consumers_active(self) -> bool:
        """False when every consumer of the expensive record extras is
        gone — a metrics-only session whose sink died on an I/O error.
        The engine then skips the per-iteration stream sync + device
        stat fetches instead of formatting payloads that get dropped."""
        return not (self.sink_disabled and self.tracer is None
                    and self.server is None and self.flight is None
                    and not self.profile_dir)

    def end_iteration(self, iteration: int,
                      extra: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        self._sample_environment()
        if self._fleet_last is not None:
            extra = dict(extra) if extra else {}
            extra.setdefault("fleet", self._fleet_last)
        try:
            rec = self.registry.end_iteration(extra=extra)
        finally:
            # a raising registry must not leak the open step annotation
            self._exit_step()
            if self.tracer is not None:
                tr = self.tracer
                tr.complete(f"iteration {iteration}", "iteration",
                            self._iter_t0_ns, tr.now_ns())
                tr.iteration = -1
        if self.sink is not None and iteration % self.interval == 0:
            if self.sink.disabled:
                # short-circuit: count the drop, skip serialization
                self.sink.dropped += 1
                self.registry.inc("sink.dropped_payloads")
            else:
                self.sink.write(rec)
        if self.flight is not None:
            self.flight.observe_iteration(iteration, rec["t_iter_s"])
        return rec

    def _sample_environment(self) -> None:
        """Per-iteration device-memory + collective-shape samples
        (metrics/trace mode only — the disabled path never runs this).
        Gauges land in the registry (schema minor 5 `mem.*` / `coll.*`)
        and, when tracing, as counter events on the timeline."""
        reg = self.registry
        live = live_array_bytes()
        if live >= 0:
            self._mem_peak = max(self._mem_peak, live)
            reg.set_gauge("mem.live_bytes", live)
            reg.set_gauge("mem.live_peak_bytes", self._mem_peak)
            if self.tracer is not None:
                self.tracer.counter("mem.live_bytes", live, "bytes")
        p99 = reg.coll_p99_ms()
        if p99 is not None:
            reg.set_gauge("coll.p99_ms", round(p99, 3))
        try:
            if self.tracer is not None:
                dt_s = (self.tracer.now_ns() - self._iter_t0_ns) / 1e9
            else:
                import time as _time
                dt_s = _time.perf_counter() - reg._iter_t0
            if self.fleet_agg is not None:
                # the fleet payload rides the allgather straggler_stats
                # used to own — same single blocking sync, wider
                # payload; sets coll.host_skew / coll.slowest_rank (the
                # watchdog still NAMEs the straggler from the gauges,
                # schema minor 8) and yields the per-rank table
                self._fleet_last = self.fleet_agg.step(reg, dt_s)
            else:
                from ..network import straggler_stats
                skew, slowest = straggler_stats(dt_s)
                reg.set_gauge("coll.host_skew", skew)
                reg.set_gauge("coll.slowest_rank", slowest)
        except Exception:
            pass
        if self.tracer is not None:
            reg.counters["trace.events"] = self.tracer.events_total
            reg.counters["trace.dropped"] = self.tracer.dropped

    def close(self) -> None:
        self._exit_step()
        if self.server is not None:
            self.server.stop()
            self.server = None
        if self.flight is not None:
            deactivate_flight(self.flight)
        if self.fleet_agg is not None:
            deactivate_aggregator(self.fleet_agg)
        uninstall_sync_tracing()
        try:
            if self.tracer is not None:
                deactivate_tracer(self.tracer)
                if self.trace_file:
                    try:
                        from ..robust.faultinject import check_fault
                        check_fault("trace.export")
                        self.tracer.export(self.trace_file)
                    except OSError as exc:
                        from ..utils import log
                        log.warning("trace_file=%s: export failed: %s",
                                    self.trace_file, exc)
            if self.profile_dir:
                stop_profiler()
        finally:
            if self.sink is not None:
                self.sink.close()
            deactivate(self.registry)
            if self._prev_registry is not None:
                activate(self._prev_registry)
                self._prev_registry = None
            self._started = False

    def _exit_step(self) -> None:
        if self._step is not None:
            self._step.__exit__(None, None, None)
            self._step = None
