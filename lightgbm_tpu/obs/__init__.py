"""Unified training telemetry (docs/OBSERVABILITY.md).

Layers:

- `MetricsRegistry` (obs/registry.py): counters / gauges / histograms
  + per-iteration snapshots; one process-global active registry that
  instrumentation reads with a single `is None` check.
- `span` / `instrument_kernel` / `step_span` (obs/spans.py): scopes
  that feed the utils/timer.py table, the registry, and
  jax.profiler trace annotations at once.
- `JsonlSink` + schema validators (obs/sink.py).
- `TelemetrySession` (below): ties registry + sink + profiler to the
  engine loop, configured from `Config` (`metrics_file`,
  `profile_dir`, `metrics_interval`).

Everything is off by default: with no active registry, no timer, and
no profile dir, the instrumentation fast paths reduce to a global
load per call.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from .registry import MetricsRegistry, activate, active, deactivate
from .sink import (SCHEMA_MINOR, SCHEMA_VERSION, JsonlSink, read_jsonl,
                   validate_bench_record, validate_record)
from .spans import (instrument_kernel, span, start_profiler, step_span,
                    stop_profiler)

__all__ = [
    "MetricsRegistry", "activate", "active", "deactivate",
    "SCHEMA_VERSION", "SCHEMA_MINOR", "JsonlSink", "read_jsonl",
    "validate_record",
    "validate_bench_record", "span", "step_span", "instrument_kernel",
    "start_profiler", "stop_profiler", "TelemetrySession",
]


class TelemetrySession:
    """Per-train() telemetry: activates a registry, opens the JSONL
    sink, optionally starts a jax.profiler trace, and snapshots every
    iteration. Built by the engine when the Config enables any of it;
    `from_config` returns None otherwise so the disabled path costs
    nothing."""

    def __init__(self, metrics_file: str = "", profile_dir: str = "",
                 interval: int = 1,
                 registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry if registry is not None else MetricsRegistry()
        self.sink = JsonlSink(metrics_file) if metrics_file else None
        self.interval = max(1, int(interval))
        self.profile_dir = profile_dir
        self._step = None
        self._started = False

    @classmethod
    def from_config(cls, cfg: Any) -> Optional["TelemetrySession"]:
        metrics_file = getattr(cfg, "metrics_file", "") or ""
        profile_dir = getattr(cfg, "profile_dir", "") or ""
        if not metrics_file and not profile_dir:
            return None
        return cls(metrics_file, profile_dir,
                   getattr(cfg, "metrics_interval", 1))

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._started:
            return
        activate(self.registry)
        if self.profile_dir:
            start_profiler(self.profile_dir)
        self._started = True

    def begin_iteration(self, iteration: int) -> None:
        self._exit_step()
        self._step = step_span(iteration)
        self._step.__enter__()
        self.registry.begin_iteration(iteration)

    def end_iteration(self, iteration: int,
                      extra: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        rec = self.registry.end_iteration(extra=extra)
        self._exit_step()
        if self.sink is not None and iteration % self.interval == 0:
            self.sink.write(rec)
        return rec

    def close(self) -> None:
        self._exit_step()
        if self.profile_dir:
            stop_profiler()
        if self.sink is not None:
            self.sink.close()
        deactivate(self.registry)
        self._started = False

    def _exit_step(self) -> None:
        if self._step is not None:
            self._step.__exit__(None, None, None)
            self._step = None
