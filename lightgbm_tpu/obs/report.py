"""Trace-report: summarize a runtime trace.json (obs/trace.py).

Answers the three questions a timeline is for, without opening the
Perfetto UI:

- where did the time go? — top-N slowest phase spans and per-phase
  totals,
- where did the host block? — top-N slowest sync events, grouped by
  attributed call site so one noisy site reads as one line,
- what did the interconnect do? — per-op collective count / total ms /
  max ms.

Plus the acceptance gauge: per-iteration *phase coverage*, the share of
each iteration window covered by the union of its phase intervals
(union-of-intervals, so nested/overlapping spans don't double-count).

CLI: `python -m lightgbm_tpu trace-report <trace.json> [--top N]`.
Pod-scale extras (docs/OBSERVABILITY.md):

- `trace-report --merge r0.json r1.json ... [--out merged.json]` folds
  per-rank traces into one Perfetto document (rank r => pid r) and then
  summarizes the merge,
- `trace-report --flight <dir>` summarizes a flight-recorder bundle
  (or picks the newest bundle inside a flight_dir): trigger, registry
  headline counters, and the embedded trace's report.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple


def load_trace(path: str) -> List[Dict[str, Any]]:
    """The traceEvents list of a Chrome/Perfetto trace.json (also
    accepts the bare-array form)."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        return doc
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError(f"{path}: no traceEvents array")
    return events


def _complete(events: Sequence[Dict[str, Any]],
              cat: str) -> List[Dict[str, Any]]:
    return [e for e in events
            if e.get("ph") == "X" and e.get("cat") == cat]


def _union_us(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [t0, t1] intervals (µs)."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur0, cur1 = intervals[0]
    for t0, t1 in intervals[1:]:
        if t0 > cur1:
            total += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    return total + (cur1 - cur0)


def iteration_coverage(events: Sequence[Dict[str, Any]]
                       ) -> Dict[int, float]:
    """iteration -> fraction of its window covered by the union of the
    phase intervals inside it. This is the acceptance gauge: >= 0.95
    means at most 5% of each iteration is unattributed host time."""
    windows: Dict[int, Tuple[float, float]] = {}
    for e in _complete(events, "iteration"):
        it = (e.get("args") or {}).get("iteration")
        if isinstance(it, int):
            ts = float(e["ts"])
            windows[it] = (ts, ts + float(e.get("dur", 0.0)))
    spans: Dict[int, List[Tuple[float, float]]] = {it: [] for it in windows}
    for e in _complete(events, "phase"):
        it = (e.get("args") or {}).get("iteration")
        if it in spans:
            t0, t1 = windows[it]
            s0 = max(t0, float(e["ts"]))
            s1 = min(t1, float(e["ts"]) + float(e.get("dur", 0.0)))
            if s1 > s0:
                spans[it].append((s0, s1))
    out: Dict[int, float] = {}
    for it, (t0, t1) in windows.items():
        dur = t1 - t0
        out[it] = (_union_us(spans[it]) / dur) if dur > 0 else 1.0
    return out


def _top(events: List[Dict[str, Any]], n: int) -> List[Dict[str, Any]]:
    return sorted(events, key=lambda e: -float(e.get("dur", 0.0)))[:n]


def _group_totals(events: Sequence[Dict[str, Any]]
                  ) -> List[Tuple[str, int, float, float]]:
    """(name, count, total_ms, max_ms) per event name, slowest first."""
    acc: Dict[str, List[float]] = {}
    for e in events:
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        g = acc.setdefault(e.get("name", "?"), [0, 0.0, 0.0])
        g[0] += 1
        g[1] += dur_ms
        g[2] = max(g[2], dur_ms)
    return sorted(((name, int(g[0]), g[1], g[2])
                   for name, g in acc.items()),
                  key=lambda row: -row[2])


def summarize(events: Sequence[Dict[str, Any]],
              top_n: int = 10) -> Dict[str, Any]:
    phases = _complete(events, "phase")
    syncs = _complete(events, "sync")
    colls = _complete(events, "collective")
    cov = iteration_coverage(events)
    return {
        "iterations": len(cov),
        "coverage_min": min(cov.values()) if cov else None,
        "coverage_mean": (sum(cov.values()) / len(cov)) if cov else None,
        "phase_totals": _group_totals(phases)[:top_n],
        "top_phases": _top(phases, top_n),
        "sync_totals": _group_totals(syncs)[:top_n],
        "top_syncs": _top(syncs, top_n),
        "collective_totals": _group_totals(colls)[:top_n],
        "n_events": len(events),
    }


def format_report(summary: Dict[str, Any], path: str = "") -> str:
    lines: List[str] = []
    if path:
        lines.append(f"trace report: {path}")
    lines.append(f"events: {summary['n_events']}  "
                 f"iterations: {summary['iterations']}")
    if summary["coverage_min"] is not None:
        lines.append(f"phase coverage: min {summary['coverage_min']:.1%}  "
                     f"mean {summary['coverage_mean']:.1%}")

    def table(title: str, rows: List[Tuple[str, int, float, float]]) -> None:
        if not rows:
            return
        lines.append("")
        lines.append(title)
        width = max(len(r[0]) for r in rows)
        lines.append(f"  {'name':<{width}}  {'calls':>7} "
                     f"{'total_ms':>10} {'max_ms':>9}")
        for name, cnt, total, mx in rows:
            lines.append(f"  {name:<{width}}  {cnt:>7} "
                         f"{total:>10.3f} {mx:>9.3f}")

    table("slowest phases (by total time):", summary["phase_totals"])
    table("slowest host syncs (by total time, grouped by site):",
          summary["sync_totals"])
    table("collectives:", summary["collective_totals"])
    return "\n".join(lines)


def find_bundle(path: str) -> str:
    """Resolve a flight bundle directory: either ``path`` itself (it
    holds a manifest.json) or the newest ``flight_*`` bundle inside a
    flight_dir."""
    if os.path.isfile(os.path.join(path, "manifest.json")):
        return path
    bundles = sorted(
        d for d in (os.path.join(path, n) for n in os.listdir(path))
        if os.path.basename(d).startswith("flight_")
        and os.path.isfile(os.path.join(d, "manifest.json")))
    if not bundles:
        raise ValueError(f"{path}: no flight bundle (manifest.json) found")
    return bundles[-1]


def format_flight_report(bundle: str, top_n: int = 10) -> str:
    """Human summary of one flight-recorder bundle (obs/flight.py)."""
    def _load(name: str) -> Any:
        p = os.path.join(bundle, name)
        if not os.path.isfile(p):
            return None
        with open(p) as fh:
            return json.load(fh)

    manifest = _load("manifest.json") or {}
    registry = _load("registry.json") or {}
    fleet = _load("fleet.json")
    lines = [f"flight bundle: {bundle}",
             f"trigger: {manifest.get('trigger', '?')}"]
    info = manifest.get("info") or {}
    if info:
        detail = ", ".join(f"{k}={v}" for k, v in sorted(info.items())
                           if not isinstance(v, (dict, list)))
        if detail:
            lines.append(f"info: {detail}")
    counters = registry.get("counters") or {}
    head = [k for k in ("watchdog.trips", "health.sentinel_trips",
                        "slo.breaches", "flight.dumps", "sink.dropped_payloads")
            if k in counters]
    if head:
        lines.append("counters: " + "  ".join(
            f"{k}={counters[k]:g}" for k in head))
    last = registry.get("last_record") or {}
    if last.get("iteration") is not None:
        lines.append(f"last iteration: {last['iteration']}  "
                     f"t_iter_s: {last.get('t_iter_s', float('nan')):.4g}")
    if isinstance(fleet, dict) and fleet.get("ranks"):
        lines.append(
            f"fleet: {fleet['ranks']} rank(s)  skew {fleet['skew']:.3g}  "
            f"slowest rank {fleet['slowest_rank']}")
    trace_path = os.path.join(bundle, "trace.json")
    if os.path.isfile(trace_path):
        try:
            events = load_trace(trace_path)
            lines.append("")
            lines.append(format_report(summarize(events, top_n=top_n),
                                       path=trace_path))
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            lines.append(f"trace.json unreadable: {exc}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu trace-report",
        description="Summarize a runtime trace.json "
                    "(train with trace_file=... to produce one).")
    parser.add_argument("trace", nargs="*",
                        help="path to trace.json (several with --merge)")
    parser.add_argument("--top", type=int, default=10,
                        help="rows per table (default 10)")
    parser.add_argument("--merge", action="store_true",
                        help="merge per-rank traces (rank r => pid r), "
                             "write --out, then summarize the merge")
    parser.add_argument("--out", default="merged_trace.json",
                        help="merged trace output path (default "
                             "merged_trace.json)")
    parser.add_argument("--flight", metavar="DIR",
                        help="summarize a flight-recorder bundle (or the "
                             "newest bundle inside a flight_dir)")
    ns = parser.parse_args(argv)
    if ns.flight:
        try:
            print(format_flight_report(find_bundle(ns.flight), top_n=ns.top))
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            print(f"error: {exc}")
            return 2
        return 0
    if ns.merge:
        if len(ns.trace) < 2:
            parser.error("--merge needs two or more per-rank traces")
        from .trace import merge_trace_files
        try:
            doc = merge_trace_files(ns.trace, ns.out)
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            print(f"error: {exc}")
            return 2
        print(f"merged {len(ns.trace)} rank traces -> {ns.out}")
        print(format_report(summarize(doc["traceEvents"], top_n=ns.top),
                            path=ns.out))
        return 0
    if len(ns.trace) != 1:
        parser.error("expected exactly one trace.json "
                     "(or --merge / --flight)")
    try:
        events = load_trace(ns.trace[0])
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"error: {exc}")
        return 2
    print(format_report(summarize(events, top_n=ns.top), path=ns.trace[0]))
    return 0
