"""Live observability endpoint: /metrics, /healthz, /statusz.

A stdlib `http.server` on a daemon thread (`obs_port=` param /
`--obs-port` CLI; off by default — a run without the param never
constructs a socket, imports nothing here, and pays zero overhead).
Three routes:

- `/metrics` — Prometheus text exposition format (version 0.0.4):
  registry counters as `counter`, gauges as `gauge`, and the schema
  minor 11 latency histograms as native `histogram` families with
  cumulative `le` buckets, `_sum` and `_count`.
- `/healthz` — liveness for probes: watchdog heartbeat age + trip
  state (503 once tripped), sentinel trip / quarantine counters, and
  the degraded-ladder rung (docs/ROBUSTNESS.md "Self-healing").
- `/statusz` — one JSON page for humans: iteration progress, core
  phase coverage, pipeline `overlap_share`, compile-manager stats, and
  the fleet straggler table (obs/aggregate.py).

Security: binds 127.0.0.1 by default — the pages expose host names,
file paths and config text, so widening the bind
(`LGBM_TPU_OBS_BIND=0.0.0.0`) is an explicit operator decision, never
a default (docs/OBSERVABILITY.md "Fleet plane").

Handlers READ process-global actives (registry / watchdog / fleet
aggregator / flight recorder / compile manager) at request time and
copy what they render — no locks of their own, no mutation, so a
request can never perturb the training loop beyond the GIL. The
server thread only ever blocks in `accept()`; it is marked setup-side
for the tpulint sync-point pack because it can never host a device
sync (nothing here touches jax arrays).
"""
from __future__ import annotations

import http.server
import json
import os
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils import log
from . import registry as _registry
from .registry import LATENCY_BUCKET_EDGES_MS, MetricsRegistry

BIND_ENV = "LGBM_TPU_OBS_BIND"
_PROM_PREFIX = "lgbm_tpu_"


def _prom_name(name: str) -> str:
    """Prometheus metric name: [a-zA-Z_:][a-zA-Z0-9_:]* — dots and
    dashes become underscores, anything else is dropped."""
    out = [c if c.isalnum() or c == "_" else "_"
           for c in name.replace(".", "_").replace("-", "_")]
    text = "".join(out)
    if text and text[0].isdigit():
        text = "_" + text
    return _PROM_PREFIX + text


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(reg: Optional[MetricsRegistry] = None) -> str:
    """Text exposition (0.0.4) of the registry: counters, gauges, and
    latency histograms (cumulative `le` buckets per the spec)."""
    reg = reg if reg is not None else _registry.active()
    lines: List[str] = []
    if reg is None:
        return "# no active metrics registry\n"
    for name in sorted(reg.counters):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_fmt(reg.counters[name])}")
    for name in sorted(reg.gauges):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(reg.gauges[name])}")
    for name in sorted(reg.latency_histograms()):
        h = reg.latency_histograms()[name]
        pn = _prom_name(name + "_ms")
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        counts = list(h.counts)     # copy: observe() may race the render
        for i, edge in enumerate(LATENCY_BUCKET_EDGES_MS):
            cum += counts[i]
            lines.append(f'{pn}_bucket{{le="{edge:.6g}"}} {cum}')
        cum += counts[len(LATENCY_BUCKET_EDGES_MS)]
        lines.append(f'{pn}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{pn}_sum {repr(float(h.sum))}")
        lines.append(f"{pn}_count {cum}")
    return "\n".join(lines) + "\n"


def _watchdog_state() -> Dict[str, Any]:
    try:
        from ..robust.watchdog import active_watchdog
        wd = active_watchdog()
    except Exception:
        wd = None
    if wd is None:
        return {"enabled": False}
    out: Dict[str, Any] = {"enabled": True}
    beat_t = getattr(wd, "_beat_t", None)
    if beat_t:
        out["heartbeat_age_s"] = round(time.monotonic() - beat_t, 3)
    out["iteration"] = getattr(wd, "_beat_iteration", -1)
    tripped = getattr(wd, "tripped", None)
    out["tripped"] = bool(tripped)
    if tripped:
        out["diagnosis"] = dict(tripped)
    return out


def render_healthz() -> Tuple[int, Dict[str, Any]]:
    """(http_status, body): 200 while live, 503 once the watchdog
    tripped — the orchestrator-facing kill signal."""
    wd = _watchdog_state()
    reg = _registry.active()
    counters = dict(reg.counters) if reg is not None else {}
    gauges = dict(reg.gauges) if reg is not None else {}
    degraded = int(counters.get("health.degraded", 0))
    try:
        from ..robust.sentinel import DEGRADED_LADDER
        rungs = list(DEGRADED_LADDER[:degraded])
    except Exception:
        rungs = []
    body = {
        "status": "tripped" if wd.get("tripped") else "ok",
        "watchdog": wd,
        "sentinel": {
            "trips": int(counters.get("health.sentinel_trips", 0)),
            "nan": int(counters.get("health.nan", 0)),
            "overflow": int(counters.get("health.overflow", 0)),
            "quarantined": int(counters.get("health.quarantined", 0)),
            "rollbacks": int(counters.get("health.rollbacks", 0)),
        },
        "degraded_rungs": rungs,
        "host_skew": gauges.get("coll.host_skew", 0.0),
        "flight_dumps": int(counters.get("flight.dumps", 0)),
    }
    return (503 if body["status"] == "tripped" else 200), body


def render_statusz() -> Dict[str, Any]:
    reg = _registry.active()
    body: Dict[str, Any] = {"registry_active": reg is not None}
    if reg is not None:
        rec = reg.last_record
        if rec:
            body["iteration"] = rec.get("iteration", -1)
            t_iter = rec.get("t_iter_s", 0.0)
            body["t_iter_s"] = t_iter
            if t_iter:
                core = (rec.get("t_hist_s", 0.0) + rec.get("t_split_s", 0.0)
                        + rec.get("t_partition_s", 0.0))
                body["core_phase_share"] = round(core / t_iter, 4)
        total = reg.gauges.get("train.total_iterations")
        if total:
            body["total_iterations"] = int(total)
        if "pipeline.overlap_share" in reg.gauges:
            body["overlap_share"] = reg.gauges["pipeline.overlap_share"]
        body["latency_ms"] = {
            name: {"p50": h.percentile(0.50), "p99": h.percentile(0.99)}
            for name, h in sorted(reg.latency_histograms().items())}
    try:
        from ..compile.manager import get_manager
        body["compile"] = dict(get_manager().snapshot())
    except Exception:
        pass
    try:
        from .aggregate import active_aggregator
        agg = active_aggregator()
        if agg is not None and agg.last_fleet is not None:
            body["fleet"] = dict(agg.last_fleet)
    except Exception:
        pass
    return body


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "lgbm-tpu-obs/1"

    def do_GET(self) -> None:          # noqa: N802 (stdlib contract)
        try:
            if self.path == "/metrics":
                reg = getattr(self.server, "obs_registry", None)
                body = render_prometheus(reg).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                code = 200
            elif self.path == "/healthz":
                code, doc = render_healthz()
                body = json.dumps(doc, indent=1).encode()
                ctype = "application/json"
            elif self.path == "/statusz":
                body = json.dumps(render_statusz(), indent=1).encode()
                ctype = "application/json"
                code = 200
            else:
                body = b"not found: try /metrics /healthz /statusz\n"
                ctype = "text/plain"
                code = 404
        except Exception as exc:       # a render bug must not kill probes
            body = f"render error: {exc}\n".encode()
            ctype = "text/plain"
            code = 500
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args: Any) -> None:
        log.trace("obs httpd: " + fmt, *args)


class ObsServer:
    """The daemon-thread HTTP server. `port=0` binds an ephemeral port
    (tests, the CI smoke); `start()` returns the bound port."""

    def __init__(self, port: int, registry: Optional[MetricsRegistry] = None,
                 bind: Optional[str] = None) -> None:
        self.requested_port = int(port)
        self.bind = bind if bind is not None \
            else os.environ.get(BIND_ENV, "127.0.0.1")
        self._registry = registry
        self._httpd: Optional[socketserver.TCPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        srv = http.server.ThreadingHTTPServer(
            (self.bind, self.requested_port), _Handler)
        srv.daemon_threads = True
        # explicit registry binding (tests, the CI smoke) beats the
        # process-global active; None falls through to registry.active()
        srv.obs_registry = self._registry
        self._httpd = srv
        self._thread = threading.Thread(
            target=srv.serve_forever, kwargs={"poll_interval": 0.5},
            name="lgbm-tpu-obs-httpd", daemon=True)
        self._thread.start()  # tpulint: sync-ok(setup-side daemon accept loop: serves /metrics //statusz reads, never touches jax arrays, unreachable from the hot roots)
        log.info("obs endpoint on http://%s:%d (/metrics /healthz "
                 "/statusz)", self.bind, self.port)
        return self.port

    def stop(self) -> None:
        srv, self._httpd, self._thread = self._httpd, None, None
        if srv is not None:
            try:
                srv.shutdown()
                srv.server_close()
            except Exception:
                pass
