"""span(): one scope, four consumers.

A `span` feeds (a) the `utils/timer.py` global table — same names, so
the LGBM_TPU_TIMETAG phase table is unchanged, (b) the active
`MetricsRegistry` phase times when a `phase=` is given, (c) a
`jax.profiler.TraceAnnotation` range, so host scopes line up with
device traces in XProf when `profile_dir` is set, and (d) a complete
event in the active runtime `Tracer` (obs/trace.py), so the Perfetto
timeline shows every instrumented scope in order. When none of the
consumers is enabled, a span is a bare `yield` — no annotation, no
clock read.

Exception safety: the consumer writes in the finally block run inside
their own try/finally chain, so a raising consumer (or a raising body)
can never leak an open profiler annotation or corrupt the timeline —
the annotation ALWAYS closes, and a tracer event is only appended as a
fully-formed [t0, t1] tuple. Spans nest re-entrantly: all pairing
state lives in the generator's locals.

`instrument_kernel` wraps a jitted callable once (at lru-cache build
time) so every dispatch call site is timed without editing each call;
the disabled fast path is one global load + one `is None` check.
"""
from __future__ import annotations

import contextlib
import time
from typing import Optional, Tuple

from ..utils import timer as _timer
from . import registry as _registry
from . import trace as _trace


def _trace_annotation(name: str):
    try:
        import jax.profiler
        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
        return ann
    except Exception:
        return None


@contextlib.contextmanager
def span(name: str, phase: Optional[str] = None):
    reg = _registry.active()
    gt = _timer.global_timer
    tr = _trace.active_tracer()
    if reg is None and not gt.enabled and tr is None:
        yield
        return
    ann = _trace_annotation(name)
    tr_t0 = tr.now_ns() if tr is not None else 0
    t0 = time.perf_counter()
    try:
        yield
    finally:
        # the annotation must close even when a consumer write raises
        try:
            dt = time.perf_counter() - t0
            try:
                if gt.enabled:
                    gt.acc[name] += dt
                    gt.cnt[name] += 1
                if reg is not None and phase is not None:
                    reg.add_time(phase, dt)
                    reg.observe_latency(f"lat.phase.{phase}", dt * 1e3)
            finally:
                if tr is not None:
                    tr.complete(name, "phase", tr_t0, tr.now_ns(),
                                {"phase": phase} if phase else None)
        finally:
            if ann is not None:
                ann.__exit__(None, None, None)


@contextlib.contextmanager
def step_span(iteration: int):
    """StepTraceAnnotation wrapper: marks one boosting iteration as an
    XProf "step" so the trace viewer groups device activity per
    iteration, aligned with the JSONL records."""
    ann = None
    try:
        import jax.profiler
        ann = jax.profiler.StepTraceAnnotation("boosting_iteration",
                                               step_num=int(iteration))
        ann.__enter__()
    except Exception:
        ann = None
    try:
        yield
    finally:
        if ann is not None:
            ann.__exit__(None, None, None)


def instrument_kernel(fn, phase: str, name: Optional[str] = None,
                      collective: Optional[Tuple] = None):
    """Wrap a (jitted) callable with per-call phase timing + a call
    counter, and optionally collective accounting (`collective` is
    (op_name, payload_bytes_per_call[, mesh_axis]) — bytes are computed
    at wrap time because the op runs inside traced code). Timing is
    host-side dispatch latency: under async dispatch it covers enqueue,
    on the synchronous test path it covers the compute too."""
    label = name or f"kernel/{phase}"
    if collective is not None:
        coll_op, coll_bytes = collective[0], int(collective[1])
        coll_axis = collective[2] if len(collective) > 2 else ""

    def wrapper(*args, **kwargs):
        reg = _registry.active()
        tr = _trace.active_tracer()
        if reg is None and not _timer.global_timer.enabled \
                and tr is None:
            return fn(*args, **kwargs)
        tr_t0 = tr.now_ns() if tr is not None else 0
        t0 = time.perf_counter()
        with span(label, phase=phase):
            out = fn(*args, **kwargs)
        if reg is not None:
            reg.inc(f"kernel.{phase}.calls")
            if collective is not None:
                # full collective accounting (latency histogram, axis
                # counters) — same path network.collective_span takes
                reg.record_collective(coll_op, coll_bytes,
                                      time.perf_counter() - t0,
                                      axis=coll_axis)
        if tr is not None and collective is not None:
            args_d = {"bytes": coll_bytes}
            if coll_axis:
                args_d["axis"] = coll_axis
            tr.complete(coll_op, "collective", tr_t0, tr.now_ns(), args_d)
        return out

    wrapper.__name__ = getattr(fn, "__name__", label)
    wrapper.__wrapped__ = fn
    lower = getattr(fn, "lower", None)
    if lower is not None:       # keep AOT .lower() introspection usable
        wrapper.lower = lower
    return wrapper


# -- jax.profiler programmatic trace capture ----------------------------
_PROFILING = False


def start_profiler(profile_dir: str) -> bool:
    global _PROFILING
    if _PROFILING or not profile_dir:
        return False
    try:
        import jax.profiler
        jax.profiler.start_trace(profile_dir)
        _PROFILING = True
        return True
    except Exception as exc:
        from ..utils import log
        log.warning("profile_dir=%s: could not start jax profiler: %s",
                    profile_dir, exc)
        return False


def stop_profiler() -> None:
    global _PROFILING
    if not _PROFILING:
        return
    try:
        import jax.profiler
        jax.profiler.stop_trace()
    except Exception:
        pass
    _PROFILING = False
