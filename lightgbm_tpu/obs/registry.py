"""Metrics registry: counters / gauges / histograms + per-iteration
snapshots.

The registry is the single host-side accumulation point for the
observability layer (docs/OBSERVABILITY.md): kernel wrappers
(`obs.spans.instrument_kernel`), collective accounting
(`network.collective_span`), and the training loop all write here, and
the per-iteration snapshot is what the JSONL sink serializes.

Semantics:

- counters are cumulative over the registry's lifetime (monotone),
- gauges are last-write-wins point samples,
- histograms accumulate per ITERATION (reset at `begin_iteration`) and
  snapshot as {count, sum, min, max},
- latency histograms (`observe_latency`, schema minor 11) are
  CUMULATIVE fixed-bucket log-scale distributions with derived
  p50/p90/p99 gauges — the Prometheus-exposable shape the serving
  path will gate on,
- phase times (`add_time`) are cumulative like counters; the snapshot
  reports the per-iteration DELTA of the three core tree phases
  (hist / split / partition) plus the residual `t_other_s`, so the four
  per-phase fields always sum to the iteration wall time exactly.

There is one process-global "active" registry (`activate` / `active`);
instrumentation call sites read it with a single module-attribute load,
so a disabled run pays one `is None` check per instrumented call.
"""
from __future__ import annotations

import bisect
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# retained latency samples per collective op for the p99 estimate; a
# bounded deque keeps the registry O(1)-memory over arbitrarily long
# runs (the newest samples are the ones a regression gate cares about)
_COLL_LAT_SAMPLES = 4096

# phases with first-class snapshot fields; everything else shows up in
# the snapshot's "phases" map only
CORE_PHASES = ("hist", "split", "partition")

# shared log-scale bucket upper bounds (milliseconds) for every latency
# histogram: 8 buckets per decade from 1 µs to 100 s, ratio 10^(1/8)
# ≈ 1.33 — relative quantile error is bounded by half a bucket ratio
# (~15%), constant memory, and every histogram is mergeable across
# ranks/processes because the edges are fixed at import time
LATENCY_BUCKET_EDGES_MS: Tuple[float, ...] = tuple(
    10.0 ** (e / 8.0) for e in range(-24, 41))


# tpulint: thread-ok(bucket and min/max updates are GIL-atomic; scrape threads tolerate torn reads)
class LatencyHistogram:
    """Fixed-bucket log-scale latency distribution (milliseconds).

    Cumulative over the registry lifetime (Prometheus-histogram
    semantics: monotone bucket counts). Bucket i counts observations
    `v <= LATENCY_BUCKET_EDGES_MS[i]`; one extra overflow bucket
    (`+Inf`) catches the tail. Percentiles interpolate linearly inside
    the owning bucket and clamp to the observed min/max, so small
    sample sets stay honest at the extremes.
    """

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self.counts = [0] * (len(LATENCY_BUCKET_EDGES_MS) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, ms: float) -> None:
        ms = float(ms)
        self.counts[bisect.bisect_left(LATENCY_BUCKET_EDGES_MS, ms)] += 1
        self.count += 1
        self.sum += ms
        if ms < self.min:
            self.min = ms
        if ms > self.max:
            self.max = ms

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 1]; None when empty."""
        if self.count == 0:
            return None
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i == 0:
                    lo = 0.0
                elif i >= len(LATENCY_BUCKET_EDGES_MS):
                    lo = LATENCY_BUCKET_EDGES_MS[-1]
                else:
                    lo = LATENCY_BUCKET_EDGES_MS[i - 1]
                hi = (LATENCY_BUCKET_EDGES_MS[i]
                      if i < len(LATENCY_BUCKET_EDGES_MS) else self.max)
                frac = (rank - cum) / c
                est = lo + (hi - lo) * max(0.0, min(1.0, frac))
                return min(self.max, max(self.min, est))
            cum += c
        return self.max

    def snapshot(self) -> Dict[str, Any]:
        """JSONL shape (schema minor 11): summary stats, the three
        derived percentiles, and the NONZERO buckets as [le_ms, count]
        pairs (cumulative counts would serialize 66 entries per
        histogram per iteration; sparse non-cumulative is equivalent
        information at a fraction of the bytes)."""
        buckets = []
        for i, c in enumerate(self.counts):
            if c:
                le = (LATENCY_BUCKET_EDGES_MS[i]
                      if i < len(LATENCY_BUCKET_EDGES_MS) else float("inf"))
                buckets.append([round(le, 6) if le != float("inf") else "inf",
                                c])
        return {
            "count": self.count,
            "sum_ms": round(self.sum, 6),
            "min_ms": round(self.min, 6),
            "max_ms": round(self.max, 6),
            "p50_ms": round(self.percentile(0.50) or 0.0, 6),
            "p90_ms": round(self.percentile(0.90) or 0.0, 6),
            "p99_ms": round(self.percentile(0.99) or 0.0, 6),
            "buckets": buckets,
        }


# tpulint: thread-ok(single GIL-atomic dict-slot writes; reset() runs between sessions only)
class MetricsRegistry:
    def __init__(self) -> None:
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.times: Dict[str, float] = {}       # phase -> cumulative seconds
        self._hist: Dict[str, List[float]] = {}  # name -> [cnt, sum, min, max]
        self.last_record: Optional[Dict[str, Any]] = None
        self._iteration: Optional[int] = None
        self._iter_t0 = 0.0
        self._times_at_begin: Dict[str, float] = {}
        # op -> bounded deque of host-latency seconds (schema minor 5)
        self._coll_lat: Dict[str, deque] = {}
        # name -> cumulative log-scale histogram (schema minor 11)
        self._lat: Dict[str, LatencyHistogram] = {}

    # -- accumulation ---------------------------------------------------
    def inc(self, name: str, value: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        h = self._hist.get(name)
        if h is None:
            self._hist[name] = [1, float(value), float(value), float(value)]
        else:
            h[0] += 1
            h[1] += value
            h[2] = min(h[2], value)
            h[3] = max(h[3], value)

    def add_time(self, phase: str, seconds: float) -> None:
        self.times[phase] = self.times.get(phase, 0.0) + seconds

    def observe_latency(self, name: str, ms: float) -> None:
        """Feed one sample into the cumulative log-scale histogram
        `name` (conventionally `lat.phase.<phase>` / `lat.coll.<op>` /
        `lat.fetch.<kind>`). One bisect over 65 fixed edges — cheap
        enough for every span and every device fetch."""
        h = self._lat.get(name)
        if h is None:
            h = self._lat[name] = LatencyHistogram()
        h.observe(ms)

    def latency_percentile(self, name: str, q: float) -> Optional[float]:
        """Percentile (ms) of latency histogram `name`; None when the
        histogram does not exist or is empty."""
        h = self._lat.get(name)
        return h.percentile(q) if h is not None else None

    def latency_histograms(self) -> Dict[str, LatencyHistogram]:
        """Live view for exporters (Prometheus endpoint, fleet payloads
        ); treat as read-only."""
        return self._lat

    def record_collective(self, op: str, nbytes: int, seconds: float,
                          axis: str = "") -> None:
        """One collective dispatch: call count, payload bytes (computed
        host-side — the op itself runs inside jitted code), host
        latency. `axis` is the mesh axis the op rides (schema minor 5:
        per-axis byte accounting + per-op latency histograms)."""
        self.inc(f"collective.{op}.calls")
        self.inc(f"collective.{op}.bytes", int(nbytes))
        self.add_time(f"collective.{op}", seconds)
        # per-iteration latency histogram (snapshots into "hists") +
        # bounded cumulative sample set for the session p99
        self.observe(f"coll.{op}.ms", seconds * 1e3)
        self.observe_latency(f"lat.coll.{op}", seconds * 1e3)
        lat = self._coll_lat.get(op)
        if lat is None:
            lat = self._coll_lat[op] = deque(maxlen=_COLL_LAT_SAMPLES)
        lat.append(seconds)
        if axis:
            self.inc(f"coll.axis.{axis}.calls")
            self.inc(f"coll.axis.{axis}.bytes", int(nbytes))

    def coll_p99_ms(self) -> Optional[float]:
        """p99 host latency (ms) over the retained samples of ALL
        collective ops; None when no collective ran."""
        samples: List[float] = []
        for lat in self._coll_lat.values():
            samples.extend(lat)
        if not samples:
            return None
        samples.sort()
        idx = min(len(samples) - 1, int(0.99 * (len(samples) - 1) + 0.5))
        return samples[idx] * 1e3

    # -- iteration lifecycle --------------------------------------------
    def begin_iteration(self, iteration: int,
                        now: Optional[float] = None) -> None:
        """`now` is injectable for deterministic tests."""
        self._iteration = int(iteration)
        self._iter_t0 = time.perf_counter() if now is None else now
        self._times_at_begin = dict(self.times)
        self._hist.clear()

    def end_iteration(self, now: Optional[float] = None,
                      extra: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        """Snapshot the iteration into a schema-versioned record (see
        obs/sink.py for the schema). Keys are emitted sorted so two
        registries fed identical operations produce identical records."""
        from .sink import SCHEMA_MINOR, SCHEMA_VERSION
        t1 = time.perf_counter() if now is None else now
        t_iter = max(0.0, t1 - self._iter_t0)
        # derived latency percentiles land as gauges BEFORE the gauge
        # map is copied into the record, so JSONL, /metrics and the
        # fleet payload all see the same three numbers per histogram
        for name, h in self._lat.items():
            p50 = h.percentile(0.50)
            if p50 is None:
                continue
            self.gauges[f"{name}.p50_ms"] = round(p50, 6)
            self.gauges[f"{name}.p90_ms"] = round(h.percentile(0.90), 6)
            self.gauges[f"{name}.p99_ms"] = round(h.percentile(0.99), 6)
        deltas = {ph: self.times.get(ph, 0.0)
                  - self._times_at_begin.get(ph, 0.0)
                  for ph in CORE_PHASES}
        core = sum(deltas.values())
        rec: Dict[str, Any] = {
            "schema_version": SCHEMA_VERSION,
            "schema_minor": SCHEMA_MINOR,
            "iteration": self._iteration if self._iteration is not None
            else -1,
            "t_iter_s": round(t_iter, 6),
            "t_hist_s": round(deltas["hist"], 6),
            "t_split_s": round(deltas["split"], 6),
            "t_partition_s": round(deltas["partition"], 6),
            "t_other_s": round(max(0.0, t_iter - core), 6),
            "counters": {k: self.counters[k] for k in sorted(self.counters)},
            "gauges": {k: self.gauges[k] for k in sorted(self.gauges)},
        }
        if self.times:
            rec["phases"] = {k: round(self.times[k], 6)
                             for k in sorted(self.times)}
        if self._hist:
            rec["hists"] = {
                k: {"count": int(h[0]), "sum": round(h[1], 6),
                    "min": round(h[2], 6), "max": round(h[3], 6)}
                for k, h in sorted(self._hist.items())}
        if self._lat:
            rec["lat"] = {k: self._lat[k].snapshot()
                          for k in sorted(self._lat)}
        if extra:
            rec.update(extra)
        self.last_record = rec
        self._iteration = None
        return rec

    # -- exports --------------------------------------------------------
    def bench_fields(self) -> Dict[str, Any]:
        """Per-phase breakdown for the bench.py summary line: the three
        core phase totals always (schema-stable), every other recorded
        phase and collective counter when nonzero. Keys never collide
        with the pre-existing bench keys."""
        out: Dict[str, Any] = {}
        for ph in CORE_PHASES:
            out[f"phase_{ph}_s"] = round(self.times.get(ph, 0.0), 3)
        for ph in sorted(self.times):
            if ph in CORE_PHASES or ph.startswith("collective."):
                continue
            if self.times[ph] > 0:
                out[f"phase_{ph}_s"] = round(self.times[ph], 3)
        for key in sorted(self.counters):
            if key.startswith(("collective.", "kernel.", "compile.",
                               "eval.", "hist.", "coll.", "trace.",
                               "ckpt.", "fault.", "pipeline.",
                               "watchdog.", "health.", "flight.",
                               "slo.", "sink.")):
                v = self.counters[key]
                out[key.replace(".", "_")] = int(v) if v == int(v) else v
        return out

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.times.clear()
        self._hist.clear()
        self._coll_lat.clear()
        self._lat.clear()
        self.last_record = None
        self._iteration = None


# -- process-global active registry -------------------------------------
_ACTIVE: Optional[MetricsRegistry] = None


def activate(reg: MetricsRegistry) -> MetricsRegistry:
    global _ACTIVE
    _ACTIVE = reg
    return reg


def deactivate(reg: Optional[MetricsRegistry] = None) -> None:
    """Deactivate the active registry (or only `reg`, when given and
    still active — lets nested sessions unwind safely)."""
    global _ACTIVE
    if reg is None or _ACTIVE is reg:
        _ACTIVE = None


def active() -> Optional[MetricsRegistry]:
    return _ACTIVE
