"""Schema-versioned JSONL sink + validators.

One JSON object per line, one line per (sampled) boosting iteration.
The schema is additive-only within a version: consumers must tolerate
unknown keys; removing or retyping a key bumps SCHEMA_VERSION.

Iteration record (v1.2):

  required: schema_version (int), iteration (int >= 0), t_iter_s,
            t_hist_s, t_split_s, t_partition_s, t_other_s (numbers,
            >= 0; the four phase fields sum to t_iter_s),
            counters (object of numbers), gauges (object of numbers)
  optional: schema_minor (int; additive revision within the version —
            minor 1 adds the AOT compile-manager fields: "compile.*"
            cache hit/miss/store counters and "eval.*" device-reduction
            counters under `counters`, the "compile"/"aot_load"/
            "aot_serialize" phase timers under `phases`, and "aot_*"
            manager gauges under `gauges`; minor 2 adds the
            quantized-gradient pipeline fields: "hist.quant_*"
            counters under `counters` — requantize passes, packed
            collective bytes moved, per-leaf overflow escalations —
            and the "hist.quant_bins" gauge under `gauges`; minor 3
            adds the tpulint static-analysis gauges "lint.findings" /
            "lint.baseline_size" under `gauges` and the
            "hot_loop_syncs" bench summary field; minor 4 adds the
            per-pack meshlint gauges "lint.mesh_findings" /
            "lint.tile_findings" / "lint.dtype_findings" under
            `gauges` — collective-axis, kernel-contract, and
            dtype-flow finding counts; minor 5 adds the runtime trace
            timeline fields (obs/trace.py): "trace.*" ring-buffer
            counters under `counters` — trace.events / trace.dropped —
            "mem.*" gauges under `gauges` — mem.live_bytes /
            mem.live_peak_bytes live-array HBM samples and
            mem.planar_state_bytes planar-state estimate — per-op
            "coll.{op}.ms" latency entries under `hists`, per-axis
            "coll.axis.*" counters, and the "coll.host_skew" /
            "coll.p99_ms" gauges, plus the trace_file /
            mem_peak_bytes / coll_p99_ms bench summary fields),
            phases (object: cumulative seconds per phase),
            hists (object: {count, sum, min, max}),
            lat (object, minor 11: cumulative log-scale latency
            histograms — {count, sum_ms, min_ms, max_ms, p50_ms,
            p90_ms, p99_ms, buckets: [[le_ms | "inf", count], ...]}),
            fleet (object, minor 11: pod-level view merged by
            obs/aggregate.py — ranks, iter_min/mean/max_s, skew,
            skew_trend, slowest_rank, per_rank straggler table;
            minor 12 adds the per-pack lifelint gauges
            "lint.life_findings" / "lint.thread_findings" under
            `gauges` — buffer-lifetime and thread-shared-state
            finding counts),
            metrics (object: "<dataset>/<metric>" -> number),
            num_leaves (int), best_gain (number)

`validate_bench_record` covers the bench.py summary line (BENCH_*.json
driver artifacts wrap it under a "parsed" key).
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List

SCHEMA_VERSION = 1
# additive revision within SCHEMA_VERSION (see module docstring); bumped
# to 1 when the compile-manager counters/timers joined the record, to 2
# when the quantized-gradient hist.quant_* counters/gauges joined, to 3
# when the tpulint lint.* gauges and hot_loop_syncs bench field joined,
# to 4 when the per-pack meshlint lint.{mesh,tile,dtype}_findings
# gauges joined, to 5 when the runtime trace timeline fields joined
# (trace.* counters, mem.* gauges, coll.* latency/axis accounting), to
# 6 when the fault-tolerance counters joined (ckpt.saves / ckpt.bytes /
# ckpt.write_errors / ckpt.resume / ckpt.invalid and fault.fired /
# fault.<seam> from robust/), to 7 when the async-pipeline counters
# joined (pipeline.inflight_fetches / pipeline.delayed_stop_iters /
# pipeline.donated_bytes under `counters`, the "stop_check" phase
# timer, and the overlap_share / blocking_syncs_per_iter bench summary
# fields), to 8 when the self-healing fields joined (watchdog.trips /
# watchdog.stall_<class> / watchdog.auto_resume and health.checks /
# health.sentinel_trips / health.nan / health.overflow /
# health.quarantined / health.rollbacks / health.degraded /
# health.quant_tripwire under `counters`, the "coll.slowest_rank"
# gauge, and the "sentinel" phase timer), to 9 when the compiled-
# program accounting joined (compile.programs distinct-program
# counter, compile.lowering_s cumulative trace+lower seconds, and
# compile.hlo_bytes lowered-module size of the persisted programs
# (sub-LGBM_TPU_AOT_MIN_COMPILE_S compiles skip the stat) under
# `counters`, plus the
# compile_programs / compile_lowering_s / compile_hlo_bytes bench
# summary fields), to 10 when the multi-value histogram layout fields
# joined (hist.multival_rows packed-row counter and the
# hist.layout_planar / hist.layout_multival dispatch counters under
# `counters`, the hist.row_nnz_mean occupancy gauge, plus the
# row_nnz_mean / hist_layout bench summary fields), to 11 when the
# pod-scale observability plane joined (the `lat` latency-histogram
# object with derived "lat.*.p{50,90,99}_ms" gauges, the `fleet`
# per-rank object, the flight.dumps / flight.<trigger> /
# flight.failed / slo.breaches / sink.dropped_payloads counters, plus
# the iter_p99_s / fetch_p99_ms / obs_overhead_pct bench summary
# fields), to 12 when the lifelint packs joined (the per-pack
# lint.life_findings / lint.thread_findings gauges under `gauges` —
# buffer-lifetime and thread-shared-state finding counts, matching the
# minor-4 meshlint per-pack gauges)
SCHEMA_MINOR = 12

_REQUIRED_NUM = ("t_iter_s", "t_hist_s", "t_split_s", "t_partition_s",
                 "t_other_s")
_BENCH_REQUIRED = {"metric": str, "value": (int, float), "unit": str,
                   "vs_baseline": (int, float)}
_BENCH_OPTIONAL_NUM = ("vs_baseline_with_compile", "compile_s", "rows",
                       "iters", "test_auc", "test_auc_bayes_ceiling",
                       "predict_us_per_row", "example_auc",
                       "example_auc_reference_measured",
                       "warm_start", "aot_cache_hits", "aot_cache_misses",
                       "aot_store_loads", "aot_compile_s",
                       # quantized-gradient pipeline (schema minor 2)
                       "quantized", "num_grad_quant_bins",
                       "iter_p50_s", "iter_p90_s", "hist_share",
                       # static hot-loop sync inventory (schema minor 3)
                       "hot_loop_syncs",
                       # runtime trace timeline (schema minor 5)
                       "mem_peak_bytes", "coll_p99_ms",
                       # async pipelined iteration (schema minor 7)
                       "overlap_share", "blocking_syncs_per_iter",
                       # compiled-program accounting (schema minor 9)
                       "compile_programs", "compile_lowering_s",
                       "compile_hlo_bytes",
                       # multival layout occupancy (schema minor 10)
                       "row_nnz_mean",
                       # pod-scale observability plane (schema minor 11)
                       "iter_p99_s", "fetch_p99_ms", "obs_overhead_pct")
# optional string-typed bench keys (minor 2): histogram kernel variant;
# (minor 5): runtime trace output path; (minor 10): histogram layout
# decision ("planar" | "multival")
_BENCH_OPTIONAL_STR = ("hist_method", "trace_file", "hist_layout")


def _num_map_problems(rec: Dict[str, Any], key: str,
                      required: bool) -> List[str]:
    if key not in rec:
        return [f"missing {key!r}"] if required else []
    v = rec[key]
    if not isinstance(v, dict):
        return [f"{key!r} must be an object, got {type(v).__name__}"]
    return [f"{key}[{k!r}] must be a number"
            for k, x in v.items()
            if not isinstance(x, (int, float)) or isinstance(x, bool)]


def validate_record(rec: Any) -> List[str]:
    """Problems with one iteration record ([] = valid)."""
    if not isinstance(rec, dict):
        return ["record must be a JSON object"]
    problems: List[str] = []
    sv = rec.get("schema_version")
    if not isinstance(sv, int):
        problems.append("missing/non-int 'schema_version'")
    elif sv > SCHEMA_VERSION:
        problems.append(f"schema_version {sv} is newer than supported "
                        f"{SCHEMA_VERSION}")
    if "schema_minor" in rec and (not isinstance(rec["schema_minor"], int)
                                  or isinstance(rec["schema_minor"], bool)):
        problems.append("'schema_minor' must be an int")
    it = rec.get("iteration")
    if not isinstance(it, int) or isinstance(it, bool) or it < 0:
        problems.append("'iteration' must be an int >= 0")
    for key in _REQUIRED_NUM:
        v = rec.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            problems.append(f"'{key}' must be a number")
        elif v < 0:
            problems.append(f"'{key}' must be >= 0, got {v}")
    if not problems:
        phase_sum = (rec["t_hist_s"] + rec["t_split_s"]
                     + rec["t_partition_s"] + rec["t_other_s"])
        # the residual construction makes these equal; 10% tolerance
        # admits records produced by external tools that measured the
        # phases independently
        if phase_sum > rec["t_iter_s"] * 1.1 + 1e-6:
            problems.append(
                f"phase times sum to {phase_sum:.6f}s > 110% of "
                f"t_iter_s={rec['t_iter_s']:.6f}s")
    problems += _num_map_problems(rec, "counters", required=True)
    problems += _num_map_problems(rec, "gauges", required=True)
    problems += _num_map_problems(rec, "phases", required=False)
    problems += _num_map_problems(rec, "metrics", required=False)
    if "hists" in rec:
        if not isinstance(rec["hists"], dict):
            problems.append("'hists' must be an object")
        else:
            for k, h in rec["hists"].items():
                if not isinstance(h, dict) or \
                        not all(isinstance(h.get(f), (int, float))
                                for f in ("count", "sum", "min", "max")):
                    problems.append(f"hists[{k!r}] must have numeric "
                                    "count/sum/min/max")
    if "lat" in rec:
        if not isinstance(rec["lat"], dict):
            problems.append("'lat' must be an object")
        else:
            for k, h in rec["lat"].items():
                if not isinstance(h, dict) or \
                        not all(isinstance(h.get(f), (int, float))
                                for f in ("count", "sum_ms", "p50_ms",
                                          "p90_ms", "p99_ms")):
                    problems.append(f"lat[{k!r}] must have numeric "
                                    "count/sum_ms/p50_ms/p90_ms/p99_ms")
                    continue
                buckets = h.get("buckets", [])
                if not isinstance(buckets, list) or not all(
                        isinstance(b, list) and len(b) == 2
                        and (isinstance(b[0], (int, float)) or b[0] == "inf")
                        and isinstance(b[1], int)
                        for b in buckets):
                    problems.append(f"lat[{k!r}].buckets must be "
                                    "[le_ms|\"inf\", count] pairs")
    if "fleet" in rec:
        fl = rec["fleet"]
        if not isinstance(fl, dict):
            problems.append("'fleet' must be an object")
        else:
            for f in ("ranks", "iter_min_s", "iter_mean_s", "iter_max_s",
                      "skew", "skew_trend", "slowest_rank"):
                if not isinstance(fl.get(f), (int, float)) or \
                        isinstance(fl.get(f), bool):
                    problems.append(f"fleet.{f} must be a number")
            pr = fl.get("per_rank")
            if not isinstance(pr, list) or not all(
                    isinstance(row, dict)
                    and isinstance(row.get("rank"), int)
                    and isinstance(row.get("iter_s"), (int, float))
                    and isinstance(row.get("slowest_count"), int)
                    for row in pr):
                problems.append("fleet.per_rank must be a list of "
                                "{rank, iter_s, slowest_count, ...} rows")
    return problems


def validate_bench_record(rec: Any) -> List[str]:
    """Problems with one bench.py summary line ([] = valid). Driver
    artifacts (BENCH_*.json) wrap the line under "parsed"."""
    if isinstance(rec, dict) and "parsed" in rec:
        if rec["parsed"] is None:
            # wrapper for a run that produced no summary line (rc/tail
            # describe the failure) — nothing to validate
            return []
        rec = rec["parsed"]
    if not isinstance(rec, dict):
        return ["bench record must be a JSON object"]
    problems = []
    for key, tp in _BENCH_REQUIRED.items():
        if key not in rec:
            # the nothing-completed emergency line carries only
            # metric/value/unit/vs_baseline — all four ARE required
            problems.append(f"missing {key!r}")
        elif not isinstance(rec[key], tp) or isinstance(rec[key], bool):
            problems.append(f"{key!r} must be {tp}")
    for key in _BENCH_OPTIONAL_NUM:
        if key in rec and (not isinstance(rec[key], (int, float))
                           or isinstance(rec[key], bool)):
            problems.append(f"{key!r} must be a number")
    for key in _BENCH_OPTIONAL_STR:
        if key in rec and not isinstance(rec[key], str):
            problems.append(f"{key!r} must be a string")
    for key, v in (rec.items() if isinstance(rec, dict) else ()):
        if key.startswith("phase_") and (not isinstance(v, (int, float))
                                         or isinstance(v, bool)):
            problems.append(f"{key!r} must be a number")
    return problems


class JsonlSink:
    """Append-mode JSONL writer, flushed per line so a killed run keeps
    every completed iteration.

    Telemetry must never take down training: any OSError (disk full,
    permissions, injected fault) disables the sink with ONE warning and
    every later write is a no-op. Callers that assemble expensive
    payloads should consult `disabled` FIRST (TelemetrySession does) —
    a disabled sink still counts the writes it would have taken in
    `dropped`, so silently lost telemetry shows up as the
    `sink.dropped_payloads` counter instead of a mystery gap."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.dropped = 0
        # watchdog trips and flight-recorder dumps write from their own
        # threads; RLock because the write() error path calls _disable()
        self._lock = threading.RLock()
        try:
            self._fh = open(path, "w")
        except OSError as exc:
            self._fh = None
            self._disable(exc)

    @property
    def disabled(self) -> bool:
        return self._fh is None

    def _disable(self, exc: BaseException) -> None:
        from ..utils import log
        log.warning("Metrics sink %s disabled after I/O error (%s); "
                    "training continues without JSONL metrics",
                    self.path, exc)
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def write(self, record: Dict[str, Any]) -> None:
        with self._lock:
            if self._fh is None:
                self.dropped += 1
                return
            try:
                from ..robust.faultinject import check_fault
                check_fault("sink.write")
                self._fh.write(json.dumps(record) + "\n")
                self._fh.flush()
            except OSError as exc:
                self._disable(exc)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
