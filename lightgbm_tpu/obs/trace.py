"""Runtime trace timeline: bounded ring buffer -> Perfetto trace.json.

The registry (obs/registry.py) answers "how much, in total"; this
module answers "WHEN, and in what order" — the per-iteration timeline
that docs/ROADMAP.md item 5 (async pipelined boosting) needs to judge
where the host actually blocks. Mirrors the reference's per-phase
`Common::Timer` breakdown (common.h:1054), but as structured events
rather than an end-of-run table.

Design constraints, in order:

- **Bounded memory.** Events land in a `collections.deque(maxlen=N)`
  ring: a million-iteration run keeps the LAST N events and counts the
  evictions (`dropped`), so the tracer can stay on for the whole run.
- **Low overhead.** One module-global load + `is None` check on the
  disabled path (same discipline as the active registry); an enabled
  append is two `perf_counter_ns` reads and a tuple append — no dict
  churn, no locks (deque.append is atomic under the GIL).
- **Attribution.** Sync events record the innermost *package* call
  site via the same stack-walk the tpulint runtime cross-check uses
  (`analysis.runtime_check.package_site`), so every runtime host block
  maps onto the static sync-point inventory.

Event kinds (Chrome/Perfetto trace-event JSON, `ph` field):

- "X" complete events: phases (cat "phase"), iterations (cat
  "iteration"), syncs (cat "sync"), collectives (cat "collective"),
- "C" counter events: memory samples (cat "mem"),
- "i" instant events: markers (cat "mark").

`export()` writes `{"traceEvents": [...]}` — loadable directly in
https://ui.perfetto.dev or chrome://tracing. Timestamps are in
microseconds relative to tracer construction (monotonic clock).
"""
from __future__ import annotations

import contextlib
import json
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# one row (track) per event family so the Perfetto view groups them
_TID_COUNTER = 0     # counter tracks render separately anyway
_TID_PHASE = 1
_TID_SYNC = 2
_TID_COLLECTIVE = 3
_TID_ITERATION = 4
_TRACK_NAMES = {
    _TID_PHASE: "phases",
    _TID_SYNC: "host syncs",
    _TID_COLLECTIVE: "collectives",
    _TID_ITERATION: "iterations",
}

_CAT_TID = {
    "phase": _TID_PHASE,
    "sync": _TID_SYNC,
    "collective": _TID_COLLECTIVE,
    "iteration": _TID_ITERATION,
}


# tpulint: thread-ok(deque.append with maxlen is atomic; dropped/events_total are loose tallies)
class Tracer:
    """Bounded ring buffer of trace events.

    Events are stored as plain tuples
    ``(ph, name, cat, ts_ns, dur_ns, iteration, args)`` — `ph` is the
    Chrome trace-event phase ("X" complete / "C" counter / "i"
    instant), timestamps are `time.perf_counter_ns()` relative to the
    tracer's `t0_ns`, `args` is a small dict or None.
    """

    def __init__(self, capacity: int = 262144) -> None:
        self.capacity = max(16, int(capacity))
        self.buf: deque = deque(maxlen=self.capacity)
        self.dropped = 0
        self.t0_ns = time.perf_counter_ns()
        self.iteration = -1          # set by TelemetrySession per iter
        # when set, sync events are attributed to THIS iteration instead
        # of the current one — trailing fetches (pipelined boosting)
        # resolve during iteration t+1 but belong to the dispatch at t
        self.sync_attr_iteration: Optional[int] = None
        self.events_total = 0

    # -- recording ------------------------------------------------------
    def _append(self, ev: Tuple) -> None:
        if len(self.buf) == self.capacity:
            self.dropped += 1
        self.events_total += 1
        self.buf.append(ev)

    def now_ns(self) -> int:
        return time.perf_counter_ns() - self.t0_ns

    def complete(self, name: str, cat: str, t0_ns: int, t1_ns: int,
                 args: Optional[Dict[str, Any]] = None) -> None:
        """One finished [t0, t1] scope (ph "X"). t0/t1 are `now_ns()`
        values captured by the caller — begin/end pairing happens in
        the caller's locals, so an exception between begin and end can
        drop the event but can never leave an unpaired begin in the
        buffer."""
        self._append(("X", name, cat, t0_ns, max(0, t1_ns - t0_ns),
                      self.iteration, args))

    def counter(self, name: str, value: float,
                series: str = "value") -> None:
        self._append(("C", name, "mem", self.now_ns(), 0,
                      self.iteration, {series: value}))

    def instant(self, name: str, cat: str = "mark",
                args: Optional[Dict[str, Any]] = None) -> None:
        self._append(("i", name, cat, self.now_ns(), 0,
                      self.iteration, args))

    def sync(self, func: str, site: Optional[Tuple[str, int]],
             t0_ns: int, t1_ns: int, nbytes: int = -1) -> None:
        """One host-blocking call (device_get / block_until_ready),
        attributed to its package call site so runtime events join the
        tpulint static inventory (analysis/sync_points.py)."""
        if site is not None:
            name = f"{func}@{site[0]}:{site[1]}"
            args: Dict[str, Any] = {"site": f"{site[0]}:{site[1]}"}
        else:
            name, args = func, {}
        if nbytes >= 0:
            args["bytes"] = nbytes
        it = self.iteration if self.sync_attr_iteration is None \
            else self.sync_attr_iteration
        self._append(("X", name, "sync", t0_ns, max(0, t1_ns - t0_ns),
                      it, args))

    # -- export ---------------------------------------------------------
    def to_perfetto(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (dict form). Process id 0 is used
        single-host; multi-host runs export per-process files whose pid
        is the jax process index."""
        pid = 0
        try:
            import jax
            pid = int(jax.process_index())  # tpulint: sync-ok(export-time only: to_perfetto runs once at session close, never inside the iteration loop — the hot edge is a name-collision on close() via JsonlSink._disable)
        except Exception:
            pass
        events: List[Dict[str, Any]] = [
            {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
             "args": {"name": f"lightgbm_tpu host {pid}"}},
        ]
        for tid, tname in _TRACK_NAMES.items():
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name", "args": {"name": tname}})
        for ph, name, cat, ts_ns, dur_ns, it, args in self.buf:
            ev: Dict[str, Any] = {
                "ph": ph, "name": name, "cat": cat, "pid": pid,
                "tid": _CAT_TID.get(cat, _TID_COUNTER),
                "ts": ts_ns / 1e3,          # Perfetto wants microseconds
            }
            if ph == "X":
                ev["dur"] = dur_ns / 1e3
            if ph == "i":
                ev["s"] = "t"               # thread-scoped instant
            a = dict(args) if args else {}
            if ph != "C" and it >= 0:
                a["iteration"] = it
            if a:
                ev["args"] = a
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped,
                              "events_total": self.events_total}}

    def export(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_perfetto(), fh)

    def __len__(self) -> int:
        return len(self.buf)


# -- process-global active tracer (mirrors registry.activate/active) ----
_ACTIVE: Optional[Tracer] = None


def activate_tracer(tr: Tracer) -> Tracer:
    global _ACTIVE
    _ACTIVE = tr
    return tr


def deactivate_tracer(tr: Optional[Tracer] = None) -> None:
    """Deactivate the active tracer (or only `tr`, when given and still
    active — nested sessions unwind safely)."""
    global _ACTIVE
    if tr is None or _ACTIVE is tr:
        _ACTIVE = None


def active_tracer() -> Optional[Tracer]:
    return _ACTIVE


@contextlib.contextmanager
def sync_attribution(iteration: Optional[int]):
    """Attribute sync events recorded in this scope to `iteration`
    (the DISPATCH iteration of a trailing fetch), not the iteration the
    fetch happens to resolve in. No-op when no tracer is active or
    `iteration` is None."""
    tr = _ACTIVE
    if tr is None or iteration is None or iteration < 0:
        yield
        return
    prev = tr.sync_attr_iteration
    tr.sync_attr_iteration = int(iteration)
    try:
        yield
    finally:
        tr.sync_attr_iteration = prev


# -- runtime sync tracing ------------------------------------------------
# Patches jax.device_get / jax.block_until_ready for the session so
# every hot-loop host block is timed and attributed. Reuses the
# package_site stack walk of analysis/runtime_check.py (the runtime
# cross-check that validates the static sync classification), with this
# obs subpackage skipped the same way analysis/ skips itself. Implicit
# np.asarray/__array__ transfers cannot be patched on pybind array
# types (same limitation as record_device_gets).
_SYNC_PATCH: Optional[Tuple[Any, Any]] = None


def _payload_bytes(tree: Any) -> int:
    """Best-effort payload size of a device_get argument. Guarded per
    leaf: a donated (deleted) buffer raises from `.nbytes`, and one bad
    leaf must not zero out the whole payload attribution — nor, worse,
    force a sync by touching buffer contents (metadata only here)."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        return -1
    total = 0
    for x in leaves:
        try:
            total += int(getattr(x, "nbytes", 0) or 0)
        except Exception:
            continue
    return total


def install_sync_tracing() -> bool:
    """Monkeypatch the explicit sync channel; no-op when already
    installed. Returns True when the patch is active after the call."""
    global _SYNC_PATCH
    if _SYNC_PATCH is not None:
        return True
    try:
        import jax
        from ..analysis.runtime_check import package_site
    except Exception:
        return False

    real_get, real_block = jax.device_get, jax.block_until_ready
    from . import registry as _registry
    reg_active = _registry.active

    def traced_device_get(*args, **kwargs):
        tr = _ACTIVE
        reg = reg_active()
        if tr is None and reg is None:
            return real_get(*args, **kwargs)
        t0 = time.perf_counter_ns()
        try:
            return real_get(*args, **kwargs)
        finally:
            t1 = time.perf_counter_ns()
            if reg is not None:
                # fetch-latency histogram (schema minor 11) — fed even
                # without a tracer, so `obs_port`-only sessions still
                # expose lat.fetch.* percentiles
                reg.observe_latency("lat.fetch.device_get", (t1 - t0) / 1e6)
            if tr is not None:
                tr.sync("device_get",
                        package_site(skip_dirs=("analysis", "obs")),
                        t0 - tr.t0_ns, t1 - tr.t0_ns,
                        _payload_bytes(args[0] if args else None))

    def traced_block_until_ready(*args, **kwargs):
        tr = _ACTIVE
        reg = reg_active()
        if tr is None and reg is None:
            return real_block(*args, **kwargs)
        t0 = time.perf_counter_ns()
        try:
            return real_block(*args, **kwargs)
        finally:
            t1 = time.perf_counter_ns()
            if reg is not None:
                reg.observe_latency("lat.fetch.block_until_ready",
                                    (t1 - t0) / 1e6)
            if tr is not None:
                tr.sync("block_until_ready",
                        package_site(skip_dirs=("analysis", "obs")),
                        t0 - tr.t0_ns, t1 - tr.t0_ns)

    jax.device_get = traced_device_get
    jax.block_until_ready = traced_block_until_ready
    _SYNC_PATCH = (real_get, real_block)
    return True


def uninstall_sync_tracing() -> None:
    global _SYNC_PATCH
    if _SYNC_PATCH is None:
        return
    real_get, real_block = _SYNC_PATCH
    try:
        import jax
        jax.device_get = real_get
        jax.block_until_ready = real_block
    except Exception:
        pass
    _SYNC_PATCH = None


# -- multi-rank trace merge ----------------------------------------------
def merge_trace_events(per_rank_events: List[List[Dict[str, Any]]]
                       ) -> Dict[str, Any]:
    """Merge per-rank trace-event lists into ONE Perfetto timeline with
    per-rank process tracks: input r becomes pid r (whatever pid the
    producing host wrote — files exported on different hosts can all
    carry their own process_index, or all carry 0 when each host thought
    itself alone), and the per-category track machinery (`_TRACK_NAMES`)
    is re-emitted per pid so every rank gets its own named phase / sync /
    collective / iteration rows."""
    merged: List[Dict[str, Any]] = []
    for rank, events in enumerate(per_rank_events):
        merged.append({"ph": "M", "pid": rank, "tid": 0,
                       "name": "process_name",
                       "args": {"name": f"lightgbm_tpu rank {rank}"}})
        merged.append({"ph": "M", "pid": rank, "tid": 0,
                       "name": "process_sort_index",
                       "args": {"sort_index": rank}})
        for tid, tname in _TRACK_NAMES.items():
            merged.append({"ph": "M", "pid": rank, "tid": tid,
                           "name": "thread_name", "args": {"name": tname}})
        for ev in events:
            if ev.get("ph") == "M":
                continue            # replaced by the per-rank metadata
            ev = dict(ev)
            ev["pid"] = rank
            merged.append(ev)
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "otherData": {"merged_ranks": len(per_rank_events)}}


def merge_trace_files(paths: List[str], out_path: str) -> Dict[str, Any]:
    """`trace-report --merge r0.json r1.json ...`: load each rank's
    exported trace (traceEvents dict or bare event array), merge, write
    `out_path`, return the merged document."""
    per_rank = []
    for path in paths:
        with open(path) as fh:
            doc = json.load(fh)
        per_rank.append(doc["traceEvents"] if isinstance(doc, dict)
                        else doc)
    merged = merge_trace_events(per_rank)
    with open(out_path, "w") as fh:
        json.dump(merged, fh)
    return merged


# -- device memory sampling ----------------------------------------------
def live_array_bytes() -> int:
    """Total bytes of live jax arrays in this process — the one
    HBM-footprint estimator every consumer shares (TelemetrySession
    per-iteration sampling, scripts/sparse_scale.py accounting).
    `device.memory_stats()` is not exposed through the accelerator
    tunnel, so live-array accounting is the honest portable measure;
    returns -1 when jax is unavailable."""
    try:
        import jax
        return int(sum(int(getattr(a, "nbytes", 0) or 0)
                       for a in jax.live_arrays()))
    except Exception:
        return -1
