"""Evaluation metrics.

Re-implementation of the reference metric layer (reference: src/metric/
— factory metric.cpp:16-62; regression_metric.hpp pointwise losses,
binary_metric.hpp incl. the sort-based AUC at :159, multiclass_metric.hpp,
rank_metric.hpp NDCG/MAP, xentropy_metric.hpp). Metrics are evaluated
host-side in numpy over the (converted) score array — they run once per
``metric_freq`` iterations on O(N) data, far off the hot path, and
float64 accumulation matches the reference's double sums.

Each metric returns a list of (name, value) pairs;
``bigger_is_better`` drives early stopping direction
(factor_to_bigger_better in the reference).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..config import Config
from ..utils import log


def _safe_log(x):
    return np.log(np.maximum(x, 1e-308))


def _sum_dev(x):
    """Device sum with float64-grade accumulation, for use inside jit.

    The host metric path accumulates in numpy float64; a plain f32
    `jnp.sum` over bench-scale N drifts enough to flip early-stopping
    comparisons. With x64 enabled this is a real float64 reduction; on
    the default f32 path (TPU has no f64) it runs a lane-vectorized
    Neumaier compensated sum — per-lane running compensation over
    row-chunks, then a compensated cross-lane combine — so the result
    matches the float64 sum to ~1 ulp of f32 at 10M+ elements instead
    of drifting by O(N·eps)."""
    import jax
    import jax.numpy as jnp
    if jax.config.jax_enable_x64:
        return jnp.sum(x.astype(jnp.float64))
    x = jnp.ravel(x).astype(jnp.float32)
    lanes = 1024
    pad = (-x.shape[0]) % lanes
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])

    def step(carry, row):
        s, c = carry
        t = s + row
        c = c + jnp.where(jnp.abs(s) >= jnp.abs(row),
                          (s - t) + row, (row - t) + s)
        return (t, c), None

    zero = jnp.zeros((lanes,), jnp.float32)
    (s, c), _ = jax.lax.scan(step, (zero, zero), x.reshape(-1, lanes))
    # collapsing s + c per lane would round the compensation away at
    # lane magnitude; feed sums and compensations through the scalar
    # combine separately instead
    (s1, c1), _ = jax.lax.scan(step, (jnp.float32(0), jnp.float32(0)),
                               jnp.concatenate([s, c]))
    return s1 + c1


class Metric:
    name = "metric"
    bigger_is_better = False

    def __init__(self, config: Config) -> None:
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = None if metadata.label is None else np.asarray(metadata.label)
        self.weights = None if metadata.weights is None else np.asarray(metadata.weights)
        self.sum_weights = float(np.sum(self.weights)) if self.weights is not None \
            else float(num_data)
        self.metadata = metadata

    def eval(self, score: np.ndarray, objective=None) -> List[Tuple[str, float]]:
        raise NotImplementedError

    # -- device-side evaluation (compile manager entry) ----------------
    def eval_device(self, score_dev, objective=None):
        """Reduce the metric ON DEVICE over the device-resident score:
        list of (name, 0-d device array) — so the eval loop transfers
        scalars, never the [N] score — or None when this metric has no
        device path (caller falls back to the host eval)."""
        return None

    def _label_device(self):
        import jax.numpy as jnp
        if getattr(self, "_label_dev", None) is None:
            self._label_dev = jnp.asarray(self.label, jnp.float32)
        return self._label_dev

    def _weights_device(self):
        import jax.numpy as jnp
        if self.weights is None:
            return None
        if getattr(self, "_weights_dev", None) is None:
            self._weights_dev = jnp.asarray(self.weights, jnp.float32)
        return self._weights_dev

    def _device_entry(self, suffix, objective, build):
        """Jit entry for this metric's reduction, shared through the
        compile manager: a later booster with the same config/objective
        and a same-shape score reuses the executable."""
        from ..compile import config_signature, get_manager
        sig = {"metric": self.name, "variant": suffix,
               "config": config_signature(self.config),
               "objective": (type(objective).__name__
                             if objective is not None else None)}
        return get_manager().shared_entry(
            f"eval/{self.name}{suffix}", sig, build)

    def _convert(self, score, objective):
        if objective is not None:
            import jax.numpy as jnp
            out = objective.convert_output(jnp.asarray(score))
            # tpulint: sync-ok(host-metric fallback conversion, per eval call)
            return np.asarray(out, dtype=np.float64)
        return np.asarray(score, dtype=np.float64)

    def _avg(self, loss):
        if self.weights is not None:
            return float(np.sum(loss * self.weights) / self.sum_weights)
        return float(np.mean(loss))


# --- regression pointwise metrics (regression_metric.hpp) -----------------

class _Pointwise(Metric):
    convert = True
    # jnp twin of `loss`; subclasses with a device path override it as a
    # method (np ufuncs on traced arrays would force host transfers, so
    # the numpy `loss` bodies cannot be reused under jit)
    loss_dev = None

    def loss(self, label, score):
        raise NotImplementedError

    def finalize(self, avg_loss):
        return avg_loss

    def finalize_dev(self, avg_loss):
        return avg_loss

    def eval(self, score, objective=None):
        p = self._convert(score, objective) if self.convert else np.asarray(score)
        val = self.finalize(self._avg(self.loss(self.label, p)))
        return [(self.name, val)]

    def eval_device(self, score_dev, objective=None):
        if self.loss_dev is None or self.label is None:
            return None
        import jax
        import jax.numpy as jnp
        weighted = self.weights is not None
        convert = self.convert and objective is not None

        def build():
            def fn_w(score, label, weight):
                p = objective.convert_output(score) if convert else score
                loss = self.loss_dev(label, p)
                return self.finalize_dev(
                    _sum_dev(loss * weight) / _sum_dev(weight))

            def fn(score, label):
                p = objective.convert_output(score) if convert else score
                loss = self.loss_dev(label, p)
                return self.finalize_dev(_sum_dev(loss) / loss.shape[0])
            return jax.jit(fn_w if weighted else fn)  # tpulint: jit-ok(inside a shared_entry builder; the manager dispatches this jit)

        entry = self._device_entry("/w" if weighted else "", objective,
                                   build)
        if weighted:
            val = entry(score_dev, self._label_device(),
                        self._weights_device())
        else:
            val = entry(score_dev, self._label_device())
        return [(self.name, val)]


class L2Metric(_Pointwise):
    name = "l2"

    def loss(self, y, p):
        return (p - y) ** 2

    def loss_dev(self, y, p):
        return (p - y) ** 2


class RMSEMetric(L2Metric):
    name = "rmse"

    def finalize(self, avg):
        return float(np.sqrt(avg))

    def finalize_dev(self, avg):
        import jax.numpy as jnp
        return jnp.sqrt(avg)


class L1Metric(_Pointwise):
    name = "l1"

    def loss(self, y, p):
        return np.abs(p - y)

    def loss_dev(self, y, p):
        import jax.numpy as jnp
        return jnp.abs(p - y)


class QuantileMetric(_Pointwise):
    name = "quantile"

    def loss(self, y, p):
        delta = y - p
        a = self.config.alpha
        return np.where(delta < 0, (a - 1.0) * delta, a * delta)


class HuberMetric(_Pointwise):
    name = "huber"

    def loss(self, y, p):
        diff = p - y
        a = self.config.alpha
        return np.where(np.abs(diff) <= a, 0.5 * diff * diff,
                        a * (np.abs(diff) - 0.5 * a))


class FairMetric(_Pointwise):
    name = "fair"

    def loss(self, y, p):
        x = np.abs(p - y)
        c = self.config.fair_c
        return c * x - c * c * np.log(1.0 + x / c)


class PoissonMetric(_Pointwise):
    name = "poisson"

    def loss(self, y, p):
        eps = 1e-10
        p = np.maximum(p, eps)
        return p - y * np.log(p)


class MAPEMetric(_Pointwise):
    name = "mape"

    def loss(self, y, p):
        return np.abs(y - p) / np.maximum(1.0, np.abs(y))


class GammaMetric(_Pointwise):
    name = "gamma"

    def loss(self, y, p):
        theta = -1.0 / np.maximum(p, 1e-300)
        b = -_safe_log(-theta)
        c = _safe_log(y) - _safe_log(y)  # psi=1: log(y/1) - log(y) = 0
        return -((y * theta - b) + c)


class GammaDevianceMetric(_Pointwise):
    name = "gamma_deviance"

    def loss(self, y, p):
        tmp = y / (p + 1e-9)
        return tmp - _safe_log(tmp) - 1.0

    def finalize(self, avg):
        # reference AverageLoss: sum_loss * 2 (NOT divided by weights)
        return avg * self.sum_weights * 2 if self.weights is not None \
            else avg * self.num_data * 2


class TweedieMetric(_Pointwise):
    name = "tweedie"

    def loss(self, y, p):
        rho = self.config.tweedie_variance_power
        p = np.maximum(p, 1e-10)
        return -y * np.power(p, 1 - rho) / (1 - rho) + \
            np.power(p, 2 - rho) / (2 - rho)


# --- binary metrics (binary_metric.hpp) -----------------------------------

class BinaryLoglossMetric(_Pointwise):
    name = "binary_logloss"

    def loss(self, y, p):
        is_pos = y > 0
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return np.where(is_pos, -np.log(p), -np.log(1 - p))

    def loss_dev(self, y, p):
        import jax.numpy as jnp
        p = jnp.clip(p, 1e-15, 1 - 1e-15)
        return jnp.where(y > 0, -jnp.log(p), -jnp.log(1 - p))


class BinaryErrorMetric(_Pointwise):
    name = "binary_error"

    def loss(self, y, p):
        pred_pos = p > 0.5
        return (pred_pos != (y > 0)).astype(np.float64)

    def loss_dev(self, y, p):
        import jax.numpy as jnp
        return ((p > 0.5) != (y > 0)).astype(jnp.float32)


class AUCMetric(Metric):
    """Sort-based AUC (reference binary_metric.hpp:159-260)."""
    name = "auc"
    bigger_is_better = True

    def eval(self, score, objective=None):
        s = np.asarray(score, dtype=np.float64)
        y = (self.label > 0).astype(np.float64)
        w = self.weights if self.weights is not None else np.ones_like(y)
        order = np.argsort(-s, kind="stable")
        s, y, w = s[order], y[order], w[order]
        # group ties: average rank semantics via threshold blocks
        pos_w = y * w
        neg_w = (1 - y) * w
        # unique thresholds
        _, idx_start = np.unique(-s, return_index=True)
        block = np.zeros(len(s), dtype=np.int64)
        block[idx_start] = 1
        block = np.cumsum(block) - 1
        n_blocks = block[-1] + 1 if len(s) else 0
        bp = np.bincount(block, weights=pos_w, minlength=n_blocks)
        bn = np.bincount(block, weights=neg_w, minlength=n_blocks)
        total_neg = neg_w.sum()
        # correctly-ordered pairs: positives vs lower-scored negatives,
        # ties (same block) count half
        cum_neg_after = total_neg - np.cumsum(bn)
        acc = np.sum(bp * (cum_neg_after + 0.5 * bn))
        total_pos = pos_w.sum()
        if total_pos <= 0 or total_neg <= 0:
            log.warning("AUC: data contains only one class")
            return [(self.name, 1.0)]
        return [(self.name, float(acc / (total_pos * total_neg)))]

    def eval_device(self, score_dev, objective=None):
        """Device AUC with the same tie-block semantics as the host
        path (scores are f32 on both sides, so tie blocks agree).

        Totals and the pair accumulator go through `_sum_dev` for
        f64-grade accuracy; the per-block cumsum stays f32 — exact for
        unweighted data below 2^24 rows (counts are integers), and
        within ~1e-6 relative for weighted data."""
        if self.label is None:
            return None
        import jax
        import jax.numpy as jnp
        weighted = self.weights is not None

        def build():
            def fn(score, label, weight):
                s = score.astype(jnp.float32)
                y = (label > 0).astype(jnp.float32)
                order = jnp.argsort(-s)
                s, y, w = s[order], y[order], weight[order]
                pos_w, neg_w = y * w, (1.0 - y) * w
                start = jnp.concatenate(
                    [jnp.ones(1, jnp.int32),
                     (s[1:] != s[:-1]).astype(jnp.int32)])
                block = jnp.cumsum(start) - 1
                n = s.shape[0]
                bp = jax.ops.segment_sum(pos_w, block, num_segments=n)
                bn = jax.ops.segment_sum(neg_w, block, num_segments=n)
                total_pos = _sum_dev(pos_w).astype(jnp.float32)
                total_neg = _sum_dev(neg_w).astype(jnp.float32)
                cum_neg_after = total_neg - jnp.cumsum(bn)
                acc = _sum_dev(bp * (cum_neg_after + 0.5 * bn))
                denom = (total_pos.astype(acc.dtype)
                         * total_neg.astype(acc.dtype))
                return jnp.where(denom > 0, acc / denom, 1.0)
            if weighted:
                return jax.jit(fn)  # tpulint: jit-ok(inside a shared_entry builder; the manager dispatches this jit)
            return jax.jit(  # tpulint: jit-ok(inside a shared_entry builder; the manager dispatches this jit)
                lambda score, label: fn(score, label,
                                        jnp.ones_like(label)))

        entry = self._device_entry("/w" if weighted else "", objective,
                                   build)
        if weighted:
            val = entry(score_dev, self._label_device(),
                        self._weights_device())
        else:
            val = entry(score_dev, self._label_device())
        return [(self.name, val)]


# --- multiclass (multiclass_metric.hpp) -----------------------------------

class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective=None):
        p = self._convert_mc(score, objective)
        lab = self.label.astype(np.int64)
        rows = np.arange(len(lab))
        loss = -_safe_log(np.clip(p[rows, lab], 1e-15, 1.0))
        return [(self.name, self._avg(loss))]

    def _convert_mc(self, score, objective):
        """score arrives as [num_class, N]; convert to [N, num_class]
        probabilities."""
        s = np.asarray(score, dtype=np.float64)
        if s.ndim == 1:
            s = s.reshape(self.config.num_class, -1)
        s = s.T
        if objective is not None:
            import jax.numpy as jnp
            # tpulint: sync-ok(host-metric fallback conversion, per eval call)
            return np.asarray(objective.convert_output(jnp.asarray(s)))
        e = np.exp(s - s.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)


class MultiErrorMetric(MultiLoglossMetric):
    name = "multi_error"

    def eval(self, score, objective=None):
        p = self._convert_mc(score, objective)
        lab = self.label.astype(np.int64)
        k = max(1, self.config.multi_error_top_k)
        rows = np.arange(len(lab))
        # top-k error (reference: correct if true-class prob is among the
        # k largest, ties counted favorably)
        label_p = p[rows, lab]
        rank = np.sum(p > label_p[:, None], axis=1)
        err = (rank >= k).astype(np.float64)
        return [(self.name, self._avg(err))]


class AucMuMetric(Metric):
    """Multiclass pairwise AUC (reference multiclass_metric.hpp:183
    AucMuMetric, the AUC-mu of Kleiman & Page 2019).

    Each class pair (i, j) is scored by its distance from the separating
    hyperplane v = W[i] - W[j] applied to the RAW class margins, where W
    is the ``auc_mu_weights`` partition-loss matrix (default: 1 - I);
    the pair AUC is P(dist_i > dist_j) with ties at half credit, and
    the result averages all K(K-1)/2 pairs. Like the reference, sample
    weights do not enter (counts only)."""
    name = "auc_mu"
    bigger_is_better = True

    def eval(self, score, objective=None):
        s = np.asarray(score, dtype=np.float64)
        nc = self.config.num_class
        if s.ndim == 1:
            s = s.reshape(nc, -1)
        # s: [C, N] class-major, the reference's score buffer layout
        lab = self.label.astype(np.int64)
        W = np.asarray(self.config.auc_mu_weights, dtype=np.float64)
        if W.size == nc * nc:
            W = W.reshape(nc, nc)
        elif W.size == 0:
            W = 1.0 - np.eye(nc)
        else:
            # reference multiclass_metric.hpp errors on a wrong-sized
            # auc_mu_weights list rather than silently ignoring it
            raise ValueError(
                f"auc_mu_weights must have num_class^2 = {nc * nc} "
                f"entries, got {W.size}")
        total = 0.0
        pairs = 0
        for i in range(nc):
            mi = lab == i
            ni = int(mi.sum())
            for j in range(i + 1, nc):
                pairs += 1
                mj = lab == j
                nj = int(mj.sum())
                if ni == 0 or nj == 0:
                    continue
                v = W[i] - W[j]
                t1 = v[i] - v[j]
                d = t1 * (v @ s)                       # [N] distances
                comb = np.concatenate([d[mi], d[mj]])
                order = np.argsort(comb, kind="stable")
                sc = comb[order]
                # average ranks over tie blocks: rank-sum AUC equals
                # P(d_i > d_j) + 0.5 * P(d_i == d_j), the reference's
                # half-credit tie rule
                starts = np.concatenate([[True], sc[1:] != sc[:-1]])
                blk = np.cumsum(starts) - 1
                counts = np.bincount(blk)
                avg_rank = np.cumsum(counts) - (counts - 1) / 2.0
                ranks = np.empty(len(comb))
                ranks[order] = avg_rank[blk]
                total += ((ranks[:ni].sum() - ni * (ni + 1) / 2.0)
                          / (ni * nj))
        return [(self.name, total / pairs if pairs else 1.0)]


# --- cross entropy (xentropy_metric.hpp) ----------------------------------

class CrossEntropyMetric(_Pointwise):
    name = "cross_entropy"

    def loss(self, y, p):
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return -y * np.log(p) - (1 - y) * np.log(1 - p)


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score, objective=None):
        s = np.asarray(score, dtype=np.float64)
        hhat = np.log1p(np.exp(s))
        w = self.weights if self.weights is not None else 1.0
        z = 1.0 - np.exp(-w * hhat)
        z = np.clip(z, 1e-15, 1 - 1e-15)
        y = self.label
        loss = -y * np.log(z) - (1 - y) * np.log(1 - z)
        return [(self.name, float(np.mean(loss)))]


class KLDivMetric(_Pointwise):
    name = "kldiv"

    def loss(self, y, p):
        p = np.clip(p, 1e-15, 1 - 1e-15)
        yy = np.clip(y, 1e-15, 1 - 1e-15)
        xent = -y * np.log(p) - (1 - y) * np.log(1 - p)
        ent = -(yy * np.log(yy) + (1 - yy) * np.log(1 - yy))
        return xent - ent


# --- ranking (rank_metric.hpp) --------------------------------------------

class NDCGMetric(Metric):
    name = "ndcg"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        from ..objective.rank import DCGCalculator
        self.dcg = DCGCalculator(self.config.label_gain)
        if metadata.query_boundaries is None:
            log.fatal("NDCG metric requires query information")
        self.boundaries = np.asarray(metadata.query_boundaries)
        self.eval_at = list(self.config.eval_at)
        # per-query weights (metadata query weights unsupported yet: uniform)

    def eval(self, score, objective=None):
        s = np.asarray(score, dtype=np.float64)
        nq = len(self.boundaries) - 1
        out = np.zeros(len(self.eval_at))
        for q in range(nq):
            b, e = self.boundaries[q], self.boundaries[q + 1]
            lab = self.label[b:e]
            for ki, k in enumerate(self.eval_at):
                maxdcg = self.dcg.cal_max_dcg_at_k(k, lab)
                if maxdcg <= 0:
                    out[ki] += 1.0
                else:
                    out[ki] += self.dcg.cal_dcg_at_k(k, lab, s[b:e]) / maxdcg
        return [(f"ndcg@{k}", float(out[ki] / nq))
                for ki, k in enumerate(self.eval_at)]


class MapMetric(Metric):
    name = "map"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("MAP metric requires query information")
        self.boundaries = np.asarray(metadata.query_boundaries)
        self.eval_at = list(self.config.eval_at)

    def eval(self, score, objective=None):
        s = np.asarray(score, dtype=np.float64)
        nq = len(self.boundaries) - 1
        out = np.zeros(len(self.eval_at))
        for q in range(nq):
            b, e = self.boundaries[q], self.boundaries[q + 1]
            lab = (self.label[b:e] > 0).astype(np.float64)
            order = np.argsort(-s[b:e], kind="stable")
            rel = lab[order]
            hits = np.cumsum(rel)
            prec = hits / (np.arange(len(rel)) + 1.0)
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(rel))
                npos = rel[:kk].sum()
                out[ki] += float(np.sum(prec[:kk] * rel[:kk]) / max(npos, 1.0))
        return [(f"map@{k}", float(out[ki] / nq))
                for ki, k in enumerate(self.eval_at)]


# --- factory (metric.cpp:16) ----------------------------------------------

_REGISTRY = {
    "l2": L2Metric, "rmse": RMSEMetric, "l1": L1Metric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "auc_mu": AucMuMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric, "kldiv": KLDivMetric,
    "ndcg": NDCGMetric, "map": MapMetric,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    cls = _REGISTRY.get(name)
    if cls is None:
        if name not in ("", "custom"):
            log.warning("Unknown metric type name: %s", name)
        return None
    return cls(config)
