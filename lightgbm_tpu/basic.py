"""User-facing Dataset and Booster.

API-compatible re-implementation of the reference Python package's core
(reference: python-package/lightgbm/basic.py — Dataset at :909 with lazy
construction `_lazy_init` :1052, Booster at :1930 with update :2315,
predict :2816, save/load :2632-2760, refit :2873). There is no ctypes/C
ABI boundary here: the "C side" is the JAX/device engine in
lightgbm_tpu.boosting / treelearner, so Dataset wraps BinnedDataset and
Booster wraps the GBDT driver directly.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config
from .io.dataset import BinnedDataset, _is_sparse
from .utils import log
from .utils.log import LightGBMError


def _to_2d_numpy(data):
    if hasattr(data, "values") and hasattr(data, "dtypes"):  # DataFrame
        return _pandas_to_numpy(data)
    if _is_sparse(data):  # consumed column-wise without densifying
        return data
    arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    if arr.dtype == object:
        arr = arr.astype(np.float64)
    return arr


def _pandas_to_numpy(df) -> np.ndarray:
    import pandas as pd
    out = np.empty(df.shape, dtype=np.float64)
    for i, col in enumerate(df.columns):
        s = df[col]
        if isinstance(s.dtype, pd.CategoricalDtype):
            out[:, i] = s.cat.codes.astype(np.float64)
            out[out[:, i] < 0, i] = np.nan
        else:
            out[:, i] = pd.to_numeric(s, errors="coerce").astype(np.float64)
    return out


def _label_from_pandas(label):
    if hasattr(label, "values"):
        return np.asarray(label.values, dtype=np.float64).reshape(-1)
    return None if label is None else np.asarray(label, dtype=np.float64).reshape(-1)


class Dataset:
    """Training data container (reference basic.py:909)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None, silent=False,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True) -> None:
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = copy.deepcopy(params) if params else {}
        self.free_raw_data = free_raw_data
        self._handle: Optional[BinnedDataset] = None
        self.used_indices: Optional[np.ndarray] = None
        self._predictor = None
        self.pandas_categorical = None

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        """Lazy construction (reference basic.py:1274)."""
        if self._handle is not None:
            return self
        if self.used_indices is not None and hasattr(self, "_subset_parent"):
            return self._construct_subset()
        if self.reference is not None:
            ref = self.reference.construct()
        else:
            ref = None
        if isinstance(self.data, str):
            self._construct_from_file(self.data, ref)
            return self
        mat = _to_2d_numpy(self.data)
        if self.used_indices is not None:
            mat = mat.tocsr()[self.used_indices] if _is_sparse(mat) \
                else mat[self.used_indices]
        cfg = Config.from_params(self.params)
        feature_names = self._resolve_feature_names(mat.shape[1])
        cat = self._resolve_categorical(feature_names)
        label = _label_from_pandas(self.label)
        weight = None if self.weight is None else np.asarray(self.weight).reshape(-1)
        group = None if self.group is None else np.asarray(self.group).reshape(-1)
        init_score = None if self.init_score is None else np.asarray(self.init_score)
        self._handle = BinnedDataset.from_matrix(
            mat, cfg, label=label, weight=weight, group=group,
            init_score=init_score, feature_names=feature_names,
            categorical_feature=cat,
            reference=None if ref is None else ref._handle)
        if self.free_raw_data:
            self.data = None
        return self

    def _construct_from_file(self, path: str, ref) -> None:
        if path.endswith(".bin"):
            self._handle = BinnedDataset.load_binary(path)
            return
        from .io.text_loader import load_text_file
        cfg = Config.from_params(self.params)
        if ref is not None and cfg.initscore_filename:
            # the initscore_filename override names the TRAINING init
            # file; validation sets keep the <data>.init sidecar
            # convention (reference metadata.cpp LoadInitialScore)
            import dataclasses
            cfg = dataclasses.replace(cfg, initscore_filename="")
        mat, label, weight, group, init_score = load_text_file(path, cfg)
        feature_names = [f"Column_{i}" for i in range(mat.shape[1])]
        cat = self._resolve_categorical(feature_names)
        self._handle = BinnedDataset.from_matrix(
            mat, cfg, label=label, weight=weight, group=group,
            init_score=init_score,
            feature_names=feature_names, categorical_feature=cat,
            reference=None if ref is None else ref._handle)

    def _resolve_feature_names(self, ncol: int) -> List[str]:
        if isinstance(self.feature_name, list):
            return list(self.feature_name)
        if self.feature_name == "auto" and hasattr(self.data, "columns"):
            return [str(c) for c in self.data.columns]
        return [f"Column_{i}" for i in range(ncol)]

    def _resolve_categorical(self, feature_names: List[str]):
        cat = self.categorical_feature
        if cat == "auto" or cat is None:
            if hasattr(self.data, "dtypes"):
                import pandas as pd
                return [i for i, c in enumerate(self.data.columns)
                        if isinstance(self.data.dtypes.iloc[i], pd.CategoricalDtype)]
            return None
        out = []
        for c in cat:
            if isinstance(c, str):
                if c in feature_names:
                    out.append(feature_names.index(c))
            else:
                out.append(int(c))
        return out

    # ------------------------------------------------------------------
    @property
    def handle(self) -> Optional[BinnedDataset]:
        return self._handle

    def num_data(self) -> int:
        self.construct()
        return self._handle.num_data

    def num_feature(self) -> int:
        self.construct()
        return self._handle.num_total_features

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._handle.feature_names)

    def get_label(self):
        if self._handle is not None and self._handle.metadata.label is not None:
            return np.asarray(self._handle.metadata.label)
        return _label_from_pandas(self.label)

    def get_weight(self):
        if self._handle is not None and self._handle.metadata.weights is not None:
            return np.asarray(self._handle.metadata.weights)
        return self.weight

    def get_group(self):
        if self._handle is not None and self._handle.metadata.query_boundaries is not None:
            return np.diff(self._handle.metadata.query_boundaries)
        return self.group

    def get_init_score(self):
        return self.init_score

    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._handle is not None:
            self._handle.metadata.set_label(_label_from_pandas(label))
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._handle is not None:
            self._handle.metadata.set_weights(
                None if weight is None else np.asarray(weight).reshape(-1))
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._handle is not None:
            self._handle.metadata.set_query(
                None if group is None else np.asarray(group).reshape(-1))
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._handle is not None:
            self._handle.metadata.set_init_score(
                None if init_score is None else np.asarray(init_score))
        return self

    def set_field(self, field_name: str, data) -> "Dataset":
        return {"label": self.set_label, "weight": self.set_weight,
                "group": self.set_group,
                "init_score": self.set_init_score}[field_name](data)

    def get_field(self, field_name: str):
        return {"label": self.get_label, "weight": self.get_weight,
                "group": self.get_group,
                "init_score": self.get_init_score}[field_name]()

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, silent=False,
                     params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, silent=silent,
                       params=params or self.params,
                       free_raw_data=self.free_raw_data)

    def subset(self, used_indices: Sequence[int], params=None) -> "Dataset":
        """Row subset sharing this dataset's bin mappers (reference
        basic.py Dataset.subset / LGBM_DatasetGetSubset)."""
        if self.data is None and self._handle is None:
            raise LightGBMError("Cannot subset a freed dataset")
        ds = Dataset(self.data, label=self.label, reference=self,
                     weight=self.weight, group=self.group,
                     init_score=self.init_score,
                     feature_name=self.feature_name,
                     categorical_feature=self.categorical_feature,
                     params=params or self.params,
                     free_raw_data=False)
        ds.used_indices = np.asarray(sorted(used_indices), dtype=np.int64)
        ds._subset_parent = self
        return ds

    def _construct_subset(self) -> "Dataset":
        """Construct a subset using the parent's binned codes directly."""
        parent = self._subset_parent.construct()._handle
        idx = self.used_indices
        h = BinnedDataset()
        h.num_data = len(idx)
        h.num_total_features = parent.num_total_features
        h.bins = parent.bins[idx]
        h.bin_mappers = parent.bin_mappers
        h.real_feature_index = parent.real_feature_index
        h.inner_feature_index = parent.inner_feature_index
        h.feature_names = parent.feature_names
        h.max_bin = parent.max_bin
        h.bundles = parent.bundles
        from .io.dataset import Metadata
        h.metadata = Metadata(len(idx))
        if parent.metadata.label is not None:
            h.metadata.label = parent.metadata.label[idx]
        if parent.metadata.weights is not None:
            h.metadata.weights = parent.metadata.weights[idx]
        if self.group is not None:
            h.metadata.set_query(np.asarray(self.group))
        if parent.metadata.init_score is not None:
            isc = parent.metadata.init_score.reshape(-1, parent.num_data)
            h.metadata.init_score = isc[:, idx].reshape(-1)
        self._handle = h
        return self

    def save_binary(self, filename: str) -> "Dataset":
        self.construct()
        self._handle.save_binary(filename)
        return self

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """reference Dataset::AddFeaturesFrom (dataset.cpp:1465)."""
        self.construct()
        other.construct()
        a, b = self._handle, other._handle
        if a.num_data != b.num_data:
            raise LightGBMError("Cannot add features from a different-size dataset")
        abins, bbins = a.feature_bins(), b.feature_bins()
        a.bundles = None
        a.bins = np.concatenate(
            [abins, bbins.astype(abins.dtype, copy=False)], axis=1) \
            if abins.dtype == bbins.dtype else np.concatenate(
                [abins.astype(np.uint16), bbins.astype(np.uint16)], axis=1)
        a.bin_mappers = list(a.bin_mappers) + list(b.bin_mappers)
        offset = a.num_total_features
        a.real_feature_index = list(a.real_feature_index) + \
            [offset + f for f in b.real_feature_index]
        a.num_total_features += b.num_total_features
        a.inner_feature_index = {f: i for i, f in enumerate(a.real_feature_index)}
        a.feature_names = list(a.feature_names) + list(b.feature_names)
        a._device_bins = None
        return self


# ---------------------------------------------------------------------------


class Booster:
    """Gradient-boosting model handle (reference basic.py:1930)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent: bool = False) -> None:
        self.params = copy.deepcopy(params) if params else {}
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_set: Optional[Dataset] = None
        self.name_valid_sets: List[str] = []
        self._network_initialized = False

        from .boosting.gbdt import create_boosting
        from .objective.functions import create_objective
        from .metric.metrics import create_metric

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError(f"Training data should be Dataset instance, "
                                f"met {type(train_set).__name__}")
            cfg = Config.from_params(self.params)
            if train_set._handle is None:
                # dataset-level params given at train() time shape the
                # construction (max_bin, enable_bundle, ...) — reference
                # Dataset._update_params semantics: later params win
                train_set.params = {**(train_set.params or {}), **self.params}
            train_set.construct()
            self._train_set = train_set
            objective = create_objective(cfg)
            metrics = [m for m in (create_metric(nm, cfg) for nm in cfg.metric)
                       if m is not None]
            self._gbdt = create_boosting(cfg.boosting)
            self._gbdt.init(cfg, train_set._handle, objective, metrics)
            self.config = cfg
        elif model_file is not None:
            with open(model_file) as fh:
                model_str = fh.read()
            self._init_from_string(model_str)
        elif model_str is not None:
            self._init_from_string(model_str)
        else:
            raise TypeError("Need at least one training dataset or model "
                            "file or model string to create booster instance")

    def _init_from_string(self, model_str: str) -> None:
        from .boosting.gbdt import GBDT
        self._gbdt = GBDT()
        self._gbdt.load_model_from_string(model_str)
        self.config = Config.from_params(self.params) if self.params else Config()

    # -- pickling (reference basic.py Booster.__getstate__: the model
    # string IS the state; the device engine is rebuilt on load) -------
    def __getstate__(self):
        return {
            "model_str": self.model_to_string(num_iteration=-1),
            "params": self.params,
            "best_iteration": self.best_iteration,
            "best_score": self.best_score,
        }

    def __setstate__(self, state):
        self.params = state.get("params", {})
        self.best_iteration = state.get("best_iteration", -1)
        self.best_score = state.get("best_score", {})
        self._train_set = None
        # validation DATA does not survive pickling; an empty name list
        # makes eval(..., name) raise the clear "No validation set"
        # error instead of silently returning no metrics
        self.name_valid_sets = []
        self._network_initialized = False
        self._init_from_string(state["model_str"])

    # ------------------------------------------------------------------
    def set_network(self, machines, local_listen_port: int = 12400,
                    listen_time_out: int = 120, num_machines: int = 1) -> "Booster":
        """Multi-host wiring (reference basic.py:2093 set_network ->
        LGBM_NetworkInit, c_api.cpp:2262). On TPU the collective STACK
        is XLA's (psum/all_gather over ICI/DCN); what this call does is
        the process wiring: `jax.distributed.initialize` with the rank
        discovered from the machine list, fusing every host's chips
        into the one global device set (lightgbm_tpu.network; launch
        recipe in docs/MULTIHOST.md)."""
        from .network import ensure_distributed
        if isinstance(machines, (list, set)):
            machines = ",".join(str(m) for m in machines)
        ensure_distributed(machines, num_machines,
                           time_out=listen_time_out)
        self._network_initialized = True
        return self

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if not isinstance(data, Dataset):
            raise TypeError(f"Validation data should be Dataset instance, "
                            f"met {type(data).__name__}")
        data.construct()
        from .metric.metrics import create_metric
        metrics = [m for m in (create_metric(nm, self.config)
                               for nm in self.config.metric) if m is not None]
        self._gbdt.add_valid_data(data._handle, metrics)
        self.name_valid_sets.append(name)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; returns True if stopped
        (reference basic.py:2315)."""
        if train_set is not None and train_set is not self._train_set:
            raise LightGBMError("Replacing train_set is not supported yet")
        if fobj is None:
            return self._gbdt.train_one_iter()
        grad, hess = fobj(self._curr_pred_for_fobj(), self._train_set)
        return self.__boost(grad, hess)

    def _curr_pred_for_fobj(self):
        """Raw training scores handed to a custom fobj: [N] for
        single-class, [N, K] otherwise (reference passes the flat score
        array through LGBM_BoosterGetPredict)."""
        score = np.asarray(self._gbdt.get_training_score(), dtype=np.float64)
        k = self._gbdt.num_tree_per_iteration
        return score[0] if k == 1 else score.T

    def __boost(self, grad, hess) -> bool:
        grad = np.asarray(grad, dtype=np.float32)
        hess = np.asarray(hess, dtype=np.float32)
        k = self._gbdt.num_tree_per_iteration
        n = self._gbdt.num_data
        if grad.ndim == 2:  # [N, K] sklearn layout -> [K, N]
            grad, hess = grad.T, hess.T
        if grad.size != n * k:
            raise ValueError(
                f"Length of gradient ({grad.size}) doesn't match "
                f"num_data*num_class ({n * k})")
        return self._gbdt.train_one_iter(grad.reshape(k, n), hess.reshape(k, n))

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    @property
    def current_iteration(self):
        return self._gbdt.current_iteration

    def num_trees(self) -> int:
        return len(self._gbdt.models)

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_tree_per_iteration

    def num_feature(self) -> int:
        return self._gbdt.max_feature_idx + 1

    def feature_name(self) -> List[str]:
        return list(self._gbdt.feature_names_)

    # ------------------------------------------------------------------
    def eval(self, data: Dataset, name: str, feval=None):
        if data is self._train_set:
            return self.eval_train(feval)
        try:
            idx = self.name_valid_sets.index(name)
        except ValueError:
            raise LightGBMError(f"No validation set named {name}")
        return self._eval_set(f"valid_{idx}", name, feval)

    def eval_train(self, feval=None, res=None):
        return self._eval_set("training", "training", feval, res=res)

    def eval_valid(self, feval=None, res=None):
        out = []
        for i, name in enumerate(self.name_valid_sets):
            out += self._eval_set(f"valid_{i}", name, feval, res=res)
        return out

    def _eval_set(self, key: str, display_name: str, feval=None, res=None):
        # `res` lets the pipelined engine loop resolve ONE
        # begin_eval_at_iter handle and fan its rows out to every
        # dataset filter, instead of re-evaluating per call
        if res is None:
            res = self._gbdt.eval_at_iter()
        out = [(display_name, mname, val, bib)
               for ds, mname, val, bib in res if ds == key]
        if feval is not None:
            fevals = feval if isinstance(feval, list) else [feval]
            for f in fevals:
                if key == "training":
                    pred = self._inner_predict_train()
                    dset = self._train_set
                else:
                    idx = int(key.split("_")[1])
                    pred = self._inner_predict_valid(idx)
                    dset = None
                ret = f(pred, dset)
                rets = [ret] if not isinstance(ret, list) else ret
                for nm, val, bib in rets:
                    out.append((display_name, nm, val, bib))
        return out

    def _inner_predict_train(self):
        score = np.asarray(self._gbdt.get_training_score(), dtype=np.float64)
        return self._conv_eval_scores(score)

    def _inner_predict_valid(self, idx):
        score = np.asarray(self._gbdt.valid_score[idx].score, dtype=np.float64)
        return self._conv_eval_scores(score)

    def _conv_eval_scores(self, score):
        k = self._gbdt.num_tree_per_iteration
        if self._gbdt.objective is not None:
            import jax.numpy as jnp
            # tpulint: sync-ok(eval-path output conversion, once per eval call)
            conv = np.asarray(self._gbdt.objective.convert_output(
                jnp.asarray(score[0] if k == 1 else score.T)))
            return conv
        return score[0] if k == 1 else score.T

    # ------------------------------------------------------------------
    def predict(self, data, start_iteration: int = 0, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, data_has_header: bool = False,
                is_reshape: bool = True, **kwargs) -> np.ndarray:
        mat = _to_2d_numpy(data)
        if num_iteration is None:
            num_iteration = -1
        if _is_sparse(mat):
            # inference traverses raw feature values; densify sparse
            # inputs in bounded row chunks (reference predicts CSR rows
            # one at a time through the same raw-value decision path)
            csr = mat.tocsr()
            n = csr.shape[0]
            chunk = max(1024, min(max(n, 1), 1 << 16))
            parts = [self._predict_dense(
                np.asarray(csr[i:i + chunk].todense(), dtype=np.float64),
                start_iteration, num_iteration, raw_score, pred_leaf,
                pred_contrib) for i in range(0, n, chunk)]
            if not parts:
                return self._predict_dense(
                    np.zeros((0, csr.shape[1])), start_iteration,
                    num_iteration, raw_score, pred_leaf, pred_contrib)
            return np.concatenate(parts, axis=0)
        return self._predict_dense(mat, start_iteration, num_iteration,
                                   raw_score, pred_leaf, pred_contrib)

    def _predict_dense(self, mat, start_iteration, num_iteration,
                       raw_score, pred_leaf, pred_contrib) -> np.ndarray:
        if pred_leaf:
            return self._gbdt.predict_leaf_index(mat, start_iteration, num_iteration)
        if pred_contrib:
            return self._gbdt.predict_contrib(mat, start_iteration, num_iteration)
        if raw_score:
            return self._gbdt.predict_raw(mat, start_iteration, num_iteration)
        return self._gbdt.predict(mat, start_iteration, num_iteration)

    def refit(self, data, label, decay_rate: float = 0.9, **kwargs) -> "Booster":
        """reference basic.py:2873 Booster.refit."""
        mat = _to_2d_numpy(data)
        self._gbdt._materialize_models()
        leaf = self.predict(data, pred_leaf=True)
        new_params = dict(self.params)
        new_params["refit_decay_rate"] = decay_rate
        train = Dataset(mat, label=label, params=new_params,
                        free_raw_data=False)
        nb = Booster(new_params, train)
        nb._gbdt.models = [copy_tree(t) for t in self._gbdt.models]
        nb._gbdt.refit_tree(leaf)
        return nb

    # ------------------------------------------------------------------
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "Booster":
        it = self.best_iteration if num_iteration is None else num_iteration
        self._gbdt.save_model_to_file(
            filename, start_iteration, it if it and it > 0 else -1,
            0 if importance_type == "split" else 1)
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        it = self.best_iteration if num_iteration is None else num_iteration
        return self._gbdt.save_model_to_string(
            start_iteration, it if it and it > 0 else -1,
            0 if importance_type == "split" else 1)

    @classmethod
    def model_from_string(cls, model_str: str, verbose: bool = True) -> "Booster":
        return cls(model_str=model_str)

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> dict:
        g = self._gbdt
        it = self.best_iteration if num_iteration is None else num_iteration
        models = g._used_models(start_iteration, it if it and it > 0 else -1)
        return {
            "name": "tree",
            "version": "v3",
            "num_class": getattr(g, "_loaded_num_class",
                                 g.config.num_class if g.config else 1),
            "num_tree_per_iteration": g.num_tree_per_iteration,
            "label_index": g.label_idx,
            "max_feature_idx": g.max_feature_idx,
            "objective": g.objective.to_string() if g.objective else "",
            "average_output": g.average_output,
            "feature_names": list(g.feature_names_),
            "feature_infos": g._feature_infos(),
            "tree_info": [dict(tree_index=i, **t.to_json())
                          for i, t in enumerate(models)],
        }

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        imp = self._gbdt.feature_importance(
            0 if importance_type == "split" else 1,
            iteration if iteration else -1)
        if importance_type == "split":
            return imp.astype(np.int32)
        return imp

    def get_split_value_histogram(self, feature, bins=None, xgboost_style=False):
        """reference basic.py:2944."""
        if isinstance(feature, str):
            fidx = self.feature_name().index(feature)
        else:
            fidx = int(feature)
        self._gbdt._materialize_models()
        values = []
        for t in self._gbdt.models:
            ni = t.num_leaves - 1
            for i in range(ni):
                if int(t.split_feature[i]) == fidx and not t.is_categorical_node(i):
                    values.append(float(t.threshold[i]))
        values = np.asarray(values)
        if bins is None:
            bins = max(min(len(values), 32), 1)
        hist, edges = np.histogram(values, bins=bins)
        if xgboost_style:
            import pandas as pd
            return pd.DataFrame({"SplitValue": edges[1:], "Count": hist})
        return hist, edges

    def trees_to_dataframe(self):
        """reference basic.py:2132."""
        import pandas as pd
        self._gbdt._materialize_models()
        rows = []
        fn = self.feature_name()
        for ti, t in enumerate(self._gbdt.models):
            ni = t.num_leaves - 1
            for i in range(ni):
                rows.append({
                    "tree_index": ti, "node_depth": None,
                    "node_index": f"{ti}-S{i}",
                    "left_child": f"{ti}-S{t.left_child[i]}" if t.left_child[i] >= 0
                    else f"{ti}-L{~t.left_child[i]}",
                    "right_child": f"{ti}-S{t.right_child[i]}" if t.right_child[i] >= 0
                    else f"{ti}-L{~t.right_child[i]}",
                    "parent_index": None,
                    "split_feature": fn[int(t.split_feature[i])],
                    "split_gain": float(t.split_gain[i]),
                    "threshold": float(t.threshold[i]),
                    "decision_type": "==" if t.is_categorical_node(i) else "<=",
                    "missing_direction": "left" if t.default_left(i) else "right",
                    "missing_type": ["None", "Zero", "NaN"][t.missing_type(i)],
                    "value": float(t.internal_value[i]),
                    "weight": float(t.internal_weight[i]),
                    "count": int(t.internal_count[i]),
                })
            for leaf in range(t.num_leaves):
                rows.append({
                    "tree_index": ti, "node_depth": None,
                    "node_index": f"{ti}-L{leaf}",
                    "left_child": None, "right_child": None,
                    "parent_index": None, "split_feature": None,
                    "split_gain": None, "threshold": None,
                    "decision_type": None, "missing_direction": None,
                    "missing_type": None,
                    "value": float(t.leaf_value[leaf]),
                    "weight": float(t.leaf_weight[leaf]),
                    "count": int(t.leaf_count[leaf]),
                })
        return pd.DataFrame(rows)

    def free_dataset(self) -> "Booster":
        self._train_set = None
        return self

    def free_network(self) -> "Booster":
        self._network_initialized = False
        return self


def copy_tree(tree):
    import copy as _copy
    t = _copy.copy(tree)
    t.leaf_value = tree.leaf_value.copy()
    t.internal_value = tree.internal_value.copy()
    t._device = None
    return t
