"""Multi-host process wiring — the Network::Init seam, JAX-style.

Reference analogue: src/network/ builds a TCP/MPI collective stack from
a machine list and Network::Init is called before training
(application.cpp:164-175; LGBM_NetworkInit / set_network through the
C API, c_api.cpp:2262). The TPU framework needs none of that collective
code — XLA provides the collectives over ICI/DCN — but the PROCESS
wiring seam still exists: a multi-host job runs one Python process per
host, and `jax.distributed.initialize(coordinator, num_processes,
process_id)` is what fuses their local devices into the one global
device set that `jax.devices()` / `Mesh` then see.

Launch recipe (documented in docs/MULTIHOST.md): run the SAME training
script on every host with `machines=ip1:port,ip2:port,...` and
`num_machines=K` (reference-compatible parameters); rank is discovered
by matching local addresses against the machine list, exactly like the
reference's socket linker (linkers_socket.cpp:36-48). Host 0's entry
doubles as the JAX coordinator address. Alternatively set the standard
JAX env vars (JAX_COORDINATOR_ADDRESS etc.) or run under a cluster
manager jax.distributed auto-detects, and leave machines empty.
"""
from __future__ import annotations

import contextlib
import socket
import time
from typing import List, Optional, Sequence

from .utils import log


@contextlib.contextmanager
def collective_span(op: str, nbytes: int = 0, axis: str = ""):
    """Host-side accounting for one collective dispatch (psum /
    all_gather / ...). The ops themselves run inside jitted shard_map
    code where Python cannot observe them, so call sites wrap the
    DISPATCH and pass a computed byte estimate. Records per-op call
    count, bytes, and host-visible latency into the active
    MetricsRegistry (per-axis when `axis` names the mesh axis the op
    rides) and, when the runtime tracer is on, a "collective" event on
    the timeline; free when neither is active.
    """
    from .obs import registry as _registry
    from .obs import trace as _trace
    reg = _registry.active()
    tr = _trace.active_tracer()
    if reg is None and tr is None:
        yield
        return
    tr_t0 = tr.now_ns() if tr is not None else 0
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if reg is not None:
            reg.record_collective(op, nbytes, dt, axis=axis)
        if tr is not None:
            args = {"bytes": int(nbytes)}
            if axis:
                args["axis"] = axis
            tr.complete(op, "collective", tr_t0, tr.now_ns(), args)
        log.trace("collective %s: %d bytes, %.3f ms host", op, nbytes,
                  dt * 1e3)


def straggler_skew(seconds: float) -> float:
    """Cross-host skew gauge for one iteration: every host contributes
    its wall time, and the gauge is (max - min) / mean over hosts — 0.0
    means lockstep, 0.3 means the slowest host ran 30%-of-mean longer
    than the fastest (collectives make everyone wait for it).
    Single-process runs return 0.0 without touching the interconnect.

    NOTE: this is itself a host barrier (allgather), so it only runs on
    the metrics/trace path, never in the disabled-telemetry loop.
    """
    try:
        import jax
        if jax.process_count() <= 1:
            return 0.0
        import numpy as np
        from jax.experimental import multihost_utils
        times = np.asarray(
            multihost_utils.process_allgather(np.float32(seconds)),
            dtype=np.float64).ravel()
        mean = float(times.mean())
        if mean <= 0.0:
            return 0.0
        return float((times.max() - times.min()) / mean)
    except Exception:
        return 0.0


def parse_machine_list(machines: str) -> List[str]:
    """'ip1:port1,ip2:port2' -> ['ip1:port1', ...] (reference
    Config::machines / machine_list_filename format)."""
    out = []
    for part in str(machines).replace("\n", ",").split(","):
        part = part.strip()
        if part:
            out.append(part)
    return out


def local_addresses() -> List[str]:
    """Addresses that identify THIS host (hostname, resolved IPs,
    loopback) — the rank-discovery probe set (reference
    linkers_socket.cpp:36-48 matches local interface IPs the same
    way)."""
    addrs = {"127.0.0.1", "localhost"}
    try:
        host = socket.gethostname()
        addrs.add(host)
        try:
            addrs.update(info[4][0] for info in socket.getaddrinfo(
                host, None, family=socket.AF_INET))
        except socket.gaierror:
            pass
        # the address used for outward traffic (no packets are sent)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            addrs.add(s.getsockname()[0])
        except OSError:
            pass
        finally:
            s.close()
    except OSError:
        pass
    return sorted(addrs)


def resolve_rank(machines: Sequence[str],
                 local: Optional[Sequence[str]] = None) -> Optional[int]:
    """Index of this host in the machine list, or None when absent."""
    matches = resolve_rank_all(machines, local)
    return matches[0] if matches else None


def resolve_rank_all(machines: Sequence[str],
                     local: Optional[Sequence[str]] = None) -> List[int]:
    """ALL machine-list indices whose host part matches this host (more
    than one = several processes per host; the caller must disambiguate
    by an explicit process id, since the list's ports describe the
    peers' listen ports, not ours — reference linkers_socket.cpp
    disambiguates with local_listen_port)."""
    if local is None:
        local = local_addresses()
    local_set = set(local)
    return [rank for rank, entry in enumerate(machines)
            if entry.rsplit(":", 1)[0] in local_set]


def ensure_distributed(machines: str = "", num_machines: int = 1,
                       time_out: int = 120,
                       _initialize=None) -> bool:
    """Initialize jax.distributed for a real multi-host run (no-op when
    already initialized, or when the config is single-machine, or when
    every listed machine resolves to this host — the single-controller
    multi-chip case, where num_machines is only a work-partitioning
    parameter).

    Returns True when a multi-process runtime is active after the call.
    `time_out` is in MINUTES (the reference's time_out/listen_time_out
    config unit); it converts to seconds at the jax.distributed
    boundary. `_initialize` is injectable for tests (defaults to
    jax.distributed.initialize).
    """
    import jax

    if getattr(jax.distributed, "is_initialized", None) and \
            jax.distributed.is_initialized():
        return True
    if num_machines <= 1:
        return False
    mlist = parse_machine_list(machines)
    if not mlist:
        # no machine list: defer to env/cluster auto-detection only if
        # the standard env vars are present; otherwise this is the
        # single-controller case (one process drives all local chips)
        import os
        if os.environ.get("JAX_COORDINATOR_ADDRESS"):
            init = _initialize or jax.distributed.initialize
            init()   # fully env-driven
            return True
        return False
    if len(mlist) != num_machines:
        log.warning("machines lists %d entries but num_machines=%d; "
                    "using the list length", len(mlist), num_machines)
        num_machines = len(mlist)
    local = local_addresses()
    matches = resolve_rank_all(mlist, local)
    if not matches:
        log.fatal("This host's addresses %s match no entry of the "
                  "machine list %s (reference socket-linker rank "
                  "discovery)", local, mlist)
    if len(matches) == len(mlist):
        # every entry is this host: single-process multi-chip run
        log.info("All %d machine-list entries resolve locally: "
                 "single-controller mode (no jax.distributed)",
                 len(mlist))
        return False
    if len(matches) > 1:
        import os
        env_rank = os.environ.get("JAX_PROCESS_ID",
                                  os.environ.get("LGBM_TPU_RANK"))
        if env_rank is None:
            log.fatal("Machine list places %d processes on this host "
                      "(%s); set JAX_PROCESS_ID (or LGBM_TPU_RANK) to "
                      "pick this process's entry — the list's ports are "
                      "the peers' listen ports and cannot disambiguate "
                      "local processes", len(matches), matches)
        rank = int(env_rank)
        if rank not in matches:
            log.fatal("JAX_PROCESS_ID=%d is not one of this host's "
                      "machine-list entries %s", rank, matches)
    else:
        rank = matches[0]
    init = _initialize or jax.distributed.initialize
    init(coordinator_address=mlist[0], num_processes=num_machines,
         process_id=rank,
         initialization_timeout=int(time_out) * 60)
    log.info("jax.distributed initialized: rank %d/%d, coordinator %s "
             "(Network::Init analogue; collectives ride ICI/DCN via "
             "XLA)", rank, num_machines, mlist[0])
    return True
