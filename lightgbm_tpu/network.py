"""Multi-host process wiring — the Network::Init seam, JAX-style.

Reference analogue: src/network/ builds a TCP/MPI collective stack from
a machine list and Network::Init is called before training
(application.cpp:164-175; LGBM_NetworkInit / set_network through the
C API, c_api.cpp:2262). The TPU framework needs none of that collective
code — XLA provides the collectives over ICI/DCN — but the PROCESS
wiring seam still exists: a multi-host job runs one Python process per
host, and `jax.distributed.initialize(coordinator, num_processes,
process_id)` is what fuses their local devices into the one global
device set that `jax.devices()` / `Mesh` then see.

Launch recipe (documented in docs/MULTIHOST.md): run the SAME training
script on every host with `machines=ip1:port,ip2:port,...` and
`num_machines=K` (reference-compatible parameters); rank is discovered
by matching local addresses against the machine list, exactly like the
reference's socket linker (linkers_socket.cpp:36-48). Host 0's entry
doubles as the JAX coordinator address. Alternatively set the standard
JAX env vars (JAX_COORDINATOR_ADDRESS etc.) or run under a cluster
manager jax.distributed auto-detects, and leave machines empty.
"""
from __future__ import annotations

import contextlib
import os
import random
import socket
import threading
import time
from typing import List, Optional, Sequence, Tuple

from .utils import log

# bring-up retry policy (docs/MULTIHOST.md, "Preemption and retries"):
# a preempted peer restarting a few seconds late must not kill the
# whole job, so initialize is retried with exponential backoff +
# jitter. Overridable for impatient tests / patient clusters.
_INIT_RETRIES_ENV = "LGBM_TPU_INIT_RETRIES"
_DEFAULT_INIT_RETRIES = 3
_BACKOFF_BASE_S = 1.0
_BACKOFF_CAP_S = 30.0


@contextlib.contextmanager
def collective_span(op: str, nbytes: int = 0, axis: str = ""):
    """Host-side accounting for one collective dispatch (psum /
    all_gather / ...). The ops themselves run inside jitted shard_map
    code where Python cannot observe them, so call sites wrap the
    DISPATCH and pass a computed byte estimate. Records per-op call
    count, bytes, and host-visible latency into the active
    MetricsRegistry (per-axis when `axis` names the mesh axis the op
    rides) and, when the runtime tracer is on, a "collective" event on
    the timeline; free when neither is active.
    """
    from .obs import registry as _registry
    from .obs import trace as _trace
    from .robust.faultinject import check_fault
    from .robust.watchdog import active_watchdog
    wd = active_watchdog()
    with contextlib.ExitStack() as stack:
        if wd is not None:
            # watchdog phase marker: a hang inside the dispatch (or an
            # injected one at the fault seam below) classifies as a
            # "collective" stall, and leaving the span is a cooperative
            # check point for a pending trip
            stack.enter_context(wd.phase(f"collective:{op}"))
        check_fault("collective.dispatch")
        reg = _registry.active()
        tr = _trace.active_tracer()
        if reg is None and tr is None:
            yield
            return
        tr_t0 = tr.now_ns() if tr is not None else 0
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if reg is not None:
                reg.record_collective(op, nbytes, dt, axis=axis)
            if tr is not None:
                args = {"bytes": int(nbytes)}
                if axis:
                    args["axis"] = axis
                tr.complete(op, "collective", tr_t0, tr.now_ns(), args)
            log.trace("collective %s: %d bytes, %.3f ms host", op, nbytes,
                      dt * 1e3)


def straggler_skew(seconds: float) -> float:
    """Cross-host skew gauge for one iteration: every host contributes
    its wall time, and the gauge is (max - min) / mean over hosts — 0.0
    means lockstep, 0.3 means the slowest host ran 30%-of-mean longer
    than the fastest (collectives make everyone wait for it).
    Single-process runs return 0.0 without touching the interconnect.

    NOTE: this is itself a host barrier (allgather), so it only runs on
    the metrics/trace path, never in the disabled-telemetry loop.
    """
    return straggler_stats(seconds)[0]


def straggler_stats(seconds: float) -> Tuple[float, int]:
    """(skew, slowest_rank) for one iteration over the same allgather
    as :func:`straggler_skew`: the rank index of the host that took the
    longest lets the watchdog NAME the straggler at trip time instead
    of reporting an anonymous "hang" (collectives cannot run inside the
    watchdog thread — the mesh may be the thing that hung — so this is
    sampled on the telemetry path and read back from the
    ``coll.slowest_rank`` gauge)."""
    try:
        gathered = fleet_allgather([seconds])
        if gathered is None:
            return 0.0, 0
        times = gathered[:, 0]
        mean = float(times.mean())
        if mean <= 0.0:
            return 0.0, 0
        return (float((times.max() - times.min()) / mean),
                int(times.argmax()))
    except Exception:
        return 0.0, 0


def fleet_allgather(payload, _gather=None):
    """One `process_allgather` of a small per-rank float32 vector — THE
    single blocking host sync per iteration the telemetry plane is
    allowed (docs/OBSERVABILITY.md "Fleet plane"). The fleet aggregator
    (obs/aggregate.py) widens the payload that `straggler_stats` used to
    gather alone, so pod-level metrics piggyback on the already-paid
    skew barrier instead of adding a second one.

    Returns an (nranks, len(payload)) float64 array, or None on
    single-process runs (no interconnect touched). `_gather` is
    injectable for tests: it receives the local float32 vector and must
    return the stacked per-rank payloads."""
    import numpy as np
    vec = np.asarray(payload, dtype=np.float32).ravel()
    if _gather is None:
        import jax
        if jax.process_count() <= 1:
            return None
        from jax.experimental import multihost_utils
        _gather = multihost_utils.process_allgather
    out = np.asarray(_gather(vec), dtype=np.float64)
    return out.reshape(-1, vec.size)


def parse_machine_list(machines: str) -> List[str]:
    """'ip1:port1,ip2:port2' -> ['ip1:port1', ...] (reference
    Config::machines / machine_list_filename format).

    Every entry is validated up front — a malformed entry fails HERE,
    naming itself, instead of surfacing minutes later as an opaque
    coordinator timeout on every healthy host."""
    out = []
    for part in str(machines).replace("\n", ",").split(","):
        part = part.strip()
        if part:
            _validate_machine_entry(part, len(out))
            out.append(part)
    return out


def _validate_machine_entry(entry: str, index: int) -> None:
    """One machine-list entry must be host:port with a non-empty host
    and a port in 1..65535 (log.fatal otherwise, naming the entry)."""
    host, sep, port = entry.rpartition(":")
    if not sep or not host:
        log.fatal("machines entry %d (%r) is not host:port — every "
                  "entry needs an explicit port (reference "
                  "Config::machines format)", index, entry)
    try:
        port_num = int(port)
    except ValueError:
        port_num = -1
    if not 1 <= port_num <= 65535:
        log.fatal("machines entry %d (%r) has invalid port %r — "
                  "expected an integer in 1..65535", index, entry, port)


def local_addresses() -> List[str]:
    """Addresses that identify THIS host (hostname, resolved IPs,
    loopback) — the rank-discovery probe set (reference
    linkers_socket.cpp:36-48 matches local interface IPs the same
    way)."""
    addrs = {"127.0.0.1", "localhost"}
    try:
        host = socket.gethostname()
        addrs.add(host)
        try:
            addrs.update(info[4][0] for info in socket.getaddrinfo(
                host, None, family=socket.AF_INET))
        except socket.gaierror:
            pass
        # the address used for outward traffic (no packets are sent)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            addrs.add(s.getsockname()[0])
        except OSError:
            pass
        finally:
            s.close()
    except OSError:
        pass
    return sorted(addrs)


def resolve_rank(machines: Sequence[str],
                 local: Optional[Sequence[str]] = None) -> Optional[int]:
    """Index of this host in the machine list, or None when absent."""
    matches = resolve_rank_all(machines, local)
    return matches[0] if matches else None


def resolve_rank_all(machines: Sequence[str],
                     local: Optional[Sequence[str]] = None) -> List[int]:
    """ALL machine-list indices whose host part matches this host (more
    than one = several processes per host; the caller must disambiguate
    by an explicit process id, since the list's ports describe the
    peers' listen ports, not ours — reference linkers_socket.cpp
    disambiguates with local_listen_port)."""
    if local is None:
        local = local_addresses()
    local_set = set(local)
    return [rank for rank, entry in enumerate(machines)
            if entry.rsplit(":", 1)[0] in local_set]


def _classify_init_error(exc: BaseException,
                         coordinator: str,
                         rank: int,
                         num_processes: int) -> Tuple[str, str]:
    """(kind, actionable hint) for one failed initialize attempt.

    jax.distributed failures all surface as RuntimeError with a gRPC
    message buried inside; the three field failure modes need three
    different operator actions, so the message text is classified here
    rather than dumped raw."""
    text = f"{type(exc).__name__}: {exc}".lower()
    if "timed out" in text or "timeout" in text or "deadline" in text:
        return ("timeout",
                f"coordinator {coordinator} never assembled all "
                f"{num_processes} processes — a peer is down, still "
                "booting, or the machine list disagrees across hosts; "
                "check that every host runs the same list and raise "
                "time_out if peers boot slowly")
    if "refused" in text or "unavailable" in text or "unreachable" in text \
            or "no route" in text:
        return ("refused",
                f"nothing is listening at coordinator {coordinator} — "
                "host 0 has not started (or a firewall drops the port); "
                "start rank 0 first or fix the coordinator address")
    if "process id" in text or "process_id" in text or "rank" in text \
            or "already" in text or "mismatch" in text:
        return ("rank mismatch",
                f"this process claimed rank {rank} of {num_processes} "
                "but the coordinator disagrees — two hosts resolved the "
                "same rank (duplicate machine-list entry?) or "
                "num_machines differs across hosts")
    return ("unknown", "unrecognized bring-up failure; see the "
                       "underlying error above")


def _startup_health_barrier(timeout_s: float, _barrier=None) -> None:
    """Post-init health check: every process must reach this barrier
    within `timeout_s` or bring-up is declared failed.

    jax.distributed.initialize returning does NOT prove the job is
    usable — a peer can pass init and then wedge before its first
    collective. The sync runs in a daemon thread so a hung mesh cannot
    hang bring-up past the deadline; on timeout the job dies HERE with
    a bring-up diagnostic instead of minutes later inside the first
    histogram psum. `_barrier` is injectable for tests."""
    import jax
    if _barrier is None:
        if jax.process_count() <= 1:
            return

        def _barrier():
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("lgbm_tpu_startup")

    failure: List[BaseException] = []
    done = threading.Event()

    def _run() -> None:
        try:
            _barrier()
        except BaseException as exc:  # surfaced below, not swallowed
            failure.append(exc)
        finally:
            done.set()

    t = threading.Thread(target=_run, name="lgbm-tpu-startup-barrier",
                         daemon=True)
    t.start()
    if not done.wait(timeout_s):
        log.fatal("startup health barrier timed out after %.0fs: "
                  "jax.distributed initialized but the global device "
                  "sync never completed — a peer process wedged after "
                  "init (check its logs) or the ICI/DCN fabric is "
                  "unhealthy", timeout_s)
    if failure:
        log.fatal("startup health barrier failed: %s: %s",
                  type(failure[0]).__name__, failure[0])
    log.debug("startup health barrier passed (%d processes)",
              jax.process_count())


def ensure_distributed(machines: str = "", num_machines: int = 1,
                       time_out: int = 120,
                       _initialize=None, _sleep=None,
                       _barrier=None) -> bool:
    """Initialize jax.distributed for a real multi-host run (no-op when
    already initialized, or when the config is single-machine, or when
    every listed machine resolves to this host — the single-controller
    multi-chip case, where num_machines is only a work-partitioning
    parameter).

    Bring-up is guarded (docs/ROBUSTNESS.md): initialize is retried
    LGBM_TPU_INIT_RETRIES times (default 3) with exponential backoff +
    jitter — a peer restarting after preemption needs seconds, not a
    fresh job — and a post-init health barrier proves every process is
    actually reachable before training starts. Failures classify as
    timeout / refused / rank-mismatch with an actionable message.

    Returns True when a multi-process runtime is active after the call.
    `time_out` is in MINUTES (the reference's time_out/listen_time_out
    config unit); it converts to seconds at the jax.distributed
    boundary. `_initialize` / `_sleep` / `_barrier` are injectable for
    tests (defaults: jax.distributed.initialize / time.sleep / a real
    global device sync).
    """
    import jax

    if getattr(jax.distributed, "is_initialized", None) and \
            jax.distributed.is_initialized():
        return True
    if num_machines <= 1:
        return False
    mlist = parse_machine_list(machines)
    if not mlist:
        # no machine list: defer to env/cluster auto-detection only if
        # the standard env vars are present; otherwise this is the
        # single-controller case (one process drives all local chips)
        if os.environ.get("JAX_COORDINATOR_ADDRESS"):
            init = _initialize or jax.distributed.initialize
            init()   # fully env-driven
            return True
        return False
    if len(mlist) != num_machines:
        log.warning("machines lists %d entries but num_machines=%d; "
                    "using the list length", len(mlist), num_machines)
        num_machines = len(mlist)
    local = local_addresses()
    matches = resolve_rank_all(mlist, local)
    if not matches:
        log.fatal("This host's addresses %s match no entry of the "
                  "machine list %s (reference socket-linker rank "
                  "discovery)", local, mlist)
    if len(matches) == len(mlist):
        # every entry is this host: single-process multi-chip run
        log.info("All %d machine-list entries resolve locally: "
                 "single-controller mode (no jax.distributed)",
                 len(mlist))
        return False
    if len(matches) > 1:
        env_rank = os.environ.get("JAX_PROCESS_ID",
                                  os.environ.get("LGBM_TPU_RANK"))
        if env_rank is None:
            log.fatal("Machine list places %d processes on this host "
                      "(%s); set JAX_PROCESS_ID (or LGBM_TPU_RANK) to "
                      "pick this process's entry — the list's ports are "
                      "the peers' listen ports and cannot disambiguate "
                      "local processes", len(matches), matches)
        rank = int(env_rank)
        if rank not in matches:
            log.fatal("JAX_PROCESS_ID=%d is not one of this host's "
                      "machine-list entries %s", rank, matches)
    else:
        rank = matches[0]
    init = _initialize or jax.distributed.initialize
    sleep = _sleep or time.sleep
    timeout_s = int(time_out) * 60
    try:
        attempts = max(1, int(os.environ.get(_INIT_RETRIES_ENV,
                                             _DEFAULT_INIT_RETRIES)))
    except ValueError:
        attempts = _DEFAULT_INIT_RETRIES
    # rank-seeded jitter: every host backs off a different amount, so K
    # preempted peers don't re-stampede the coordinator in lockstep
    jitter_rng = random.Random(rank)
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            init(coordinator_address=mlist[0],
                 num_processes=num_machines, process_id=rank,
                 initialization_timeout=timeout_s)
            last = None
            break
        except Exception as exc:
            last = exc
            kind, hint = _classify_init_error(exc, mlist[0], rank,
                                              num_machines)
            if kind == "rank mismatch":
                # retrying cannot fix a topology disagreement
                log.fatal("jax.distributed bring-up failed (rank "
                          "mismatch): %s: %s — %s",
                          type(exc).__name__, exc, hint)
            if attempt + 1 >= attempts:
                break
            delay = min(_BACKOFF_CAP_S, _BACKOFF_BASE_S * (2 ** attempt))
            delay *= 1.0 + 0.25 * jitter_rng.random()
            log.warning("jax.distributed initialize attempt %d/%d "
                        "failed (%s): %s — retrying in %.1fs",
                        attempt + 1, attempts, kind, exc, delay)
            sleep(delay)
    if last is not None:
        kind, hint = _classify_init_error(last, mlist[0], rank,
                                          num_machines)
        log.fatal("jax.distributed bring-up failed after %d attempts "
                  "(%s): %s: %s — %s", attempts, kind,
                  type(last).__name__, last, hint)
    log.info("jax.distributed initialized: rank %d/%d, coordinator %s "
             "(Network::Init analogue; collectives ride ICI/DCN via "
             "XLA)", rank, num_machines, mlist[0])
    _startup_health_barrier(float(timeout_s), _barrier=_barrier)
    return True
