"""Binned training dataset — the TPU data plane.

TPU-native re-design of the reference Dataset/DatasetLoader/Metadata
(reference: src/io/dataset.cpp, src/io/dataset_loader.cpp, src/io/metadata.cpp,
include/LightGBM/dataset.h).  Instead of per-feature ``Bin`` objects with
virtual push/iterate calls and EFB feature-group packing into column blobs
(dataset.cpp:50-302), the whole dataset is one packed integer ndarray
``bins [num_data, num_features]`` (uint8 when every feature has <=256 bins)
that is uploaded to TPU HBM once; histogramming, split finding and
partitioning consume it as dense arrays.  Bin finding itself
(``BinMapper.find_bin``) runs host-side on a bounded sample, exactly like the
reference (bin_construct_sample_cnt, dataset_loader.cpp:527
ConstructFromSampleData).

Exclusive Feature Bundling: sparse near-mutually-exclusive features are
packed into shared uint8 bundle columns (io/efb.py; reference
dataset.cpp:50-302 GetConflictCount/FindGroups/FastFeatureBundling), so
the HBM matrix is [N, num_groups] with num_groups << num_features on
sparse data, and every histogram pass touches only the bundled columns.
scipy CSR/CSC inputs are consumed without densifying the raw floats —
only the bundled bin-code matrix is ever materialized.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import Config
from ..utils import log
from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, K_ZERO_THRESHOLD,
                      MISSING_NAN, MISSING_NONE, MISSING_ZERO, BinMapper)
from .efb import BundleTables, build_bundles


def _is_sparse(data) -> bool:
    try:
        import scipy.sparse as sp
        return sp.issparse(data)
    except ImportError:
        return False


def _csc_col(data, f: int):
    """(row_indices, values) of column ``f`` of a CSC matrix — the only
    sparse access pattern the data plane needs (reference sparse_bin.hpp
    iterates per-feature nonzeros the same way)."""
    start, end = data.indptr[f], data.indptr[f + 1]
    return data.indices[start:end], data.data[start:end]


def _reject_inf_feature(vals: np.ndarray, names, f: int) -> None:
    """±Inf feature values corrupt bin boundaries and flow silently into
    histogram sums; reject at construction, naming the column. NaN stays
    legal — it is the missing-value representation (reference
    BinMapper::ValueToBin routes NaN through the NA bin)."""
    inf = np.isinf(vals)
    if inf.any():
        log.fatal(
            "Feature '%s' (column %d) contains %d infinite value(s); "
            "replace them with NaN (missing) or clip to a finite range",
            names[f] if f < len(names) else str(f), f, int(inf.sum()))


class Metadata:
    """Per-row training metadata (reference: src/io/metadata.cpp,
    include/LightGBM/dataset.h:40-248): label, weights, query boundaries,
    init scores."""

    def __init__(self, num_data: int) -> None:
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None

    def set_label(self, label: Optional[np.ndarray]) -> None:
        if label is None:
            self.label = None
            return
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            log.fatal("Length of label (%d) != num_data (%d)", len(label), self.num_data)
        bad = ~np.isfinite(label)
        if bad.any():
            # reference metadata.cpp refuses NaN labels at load; a NaN
            # here poisons every gradient silently
            log.fatal(
                "Label contains %d non-finite value(s) (NaN/Inf), first "
                "at row %d; clean the label column before constructing "
                "the Dataset", int(bad.sum()), int(np.flatnonzero(bad)[0]))
        self.label = label

    def set_weights(self, weights: Optional[np.ndarray]) -> None:
        if weights is None:
            self.weights = None
            return
        weights = np.asarray(weights, dtype=np.float32).reshape(-1)
        if len(weights) != self.num_data:
            log.fatal("Length of weights (%d) != num_data (%d)", len(weights), self.num_data)
        self.weights = weights

    def set_init_score(self, init_score: Optional[np.ndarray]) -> None:
        if init_score is None:
            self.init_score = None
            return
        init_score = np.asarray(init_score, dtype=np.float64).reshape(-1, order="F")
        if len(init_score) % self.num_data != 0:
            log.fatal("Length of init_score is not a multiple of num_data")
        bad = ~np.isfinite(init_score)
        if bad.any():
            log.fatal(
                "init_score contains %d non-finite value(s) (NaN/Inf), "
                "first at position %d; scores must be finite",
                int(bad.sum()), int(np.flatnonzero(bad)[0]))
        self.init_score = init_score

    def set_query(self, group: Optional[np.ndarray]) -> None:
        """``group`` is per-query sizes (like the reference's group field);
        converted to boundaries (reference metadata.cpp query_boundaries_)."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).reshape(-1)
        if group.sum() != self.num_data:
            log.fatal("Sum of query counts (%d) != num_data (%d)", int(group.sum()), self.num_data)
        self.query_boundaries = np.concatenate([[0], np.cumsum(group)]).astype(np.int32)

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


class BinnedDataset:
    """The constructed training dataset: packed bin codes + metadata.

    Equivalent of a fully-loaded reference ``Dataset`` (dataset.cpp:315
    Construct + FinishLoad): ``bins`` is [num_data, num_used_features] int,
    ``bin_mappers`` holds per-used-feature mappers, ``real_feature_index``
    maps used-feature -> original column (reference used_feature_map_ inverse).
    """

    def __init__(self) -> None:
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.bins: Optional[np.ndarray] = None  # [N, G] group bin codes
        self.bin_mappers: List[BinMapper] = []
        self.real_feature_index: List[int] = []  # used idx -> original idx
        self.inner_feature_index: Dict[int, int] = {}  # original -> used or absent
        self.feature_names: List[str] = []
        self.metadata: Metadata = Metadata(0)
        self.max_bin: int = 255
        self.bundles: Optional[BundleTables] = None  # None == identity
        self._device_bins = None
        self._monotone_constraints: List[int] = []
        # construct-time row-occupancy statistics (ops/multival.py
        # OccupancyStats) driving the planar-vs-multival histogram
        # layout decision; None until a bin matrix exists
        self.occupancy = None

    # ------------------------------------------------------------------
    @property
    def num_features(self) -> int:
        return len(self.bin_mappers)

    @property
    def num_bins_per_feature(self) -> np.ndarray:
        return np.asarray([m.num_bin for m in self.bin_mappers], dtype=np.int32)

    @property
    def max_num_bin(self) -> int:
        return int(self.num_bins_per_feature.max()) if self.bin_mappers else 1

    def feature_offsets(self) -> np.ndarray:
        """Flattened per-feature bin offsets (for distributed histogram
        packing; reference Dataset group_bin_boundaries_ analogue)."""
        nb = self.num_bins_per_feature
        return np.concatenate([[0], np.cumsum(nb)]).astype(np.int32)

    def device_bins(self):
        """The packed bin matrix as a device array (uploaded once to HBM)."""
        import jax.numpy as jnp
        if self._device_bins is None:
            self._device_bins = jnp.asarray(self.bins)
        return self._device_bins

    # --- EFB views --------------------------------------------------------
    @property
    def efb_trivial(self) -> bool:
        return self.bundles is None or self.bundles.is_trivial

    @property
    def group_max_bins(self) -> int:
        """Max bin-code count over the physical bundle columns (== max
        feature num_bin when bundling is trivial)."""
        if self.efb_trivial:
            return self.max_num_bin
        return int(self.bundles.group_num_bins.max())

    def device_bundle_tables(self):
        """(group_of, offset_of, nslots_of, skip_of) device arrays, or
        None when bundling is trivial (consumers then index features
        directly — zero overhead on dense data)."""
        if self.efb_trivial:
            return None
        return self.bundles.device()

    def device_hist_tables(self):
        """Gather tables for bundle-hist → per-feature-hist conversion."""
        if self.efb_trivial:
            return None
        return self.bundles.hist_tables(
            [m.num_bin for m in self.bin_mappers], self.max_num_bin)

    def feature_bins(self) -> np.ndarray:
        """Decoded per-feature bin matrix [N, F_used] (host). Identity
        when bundling is trivial; otherwise materializes the dense view —
        used only by consumers that cannot work in bundle space
        (add_features_from, parallel-learner debundling)."""
        if self.efb_trivial:
            return self.bins
        bt = self.bundles
        f_used = len(self.bin_mappers)
        dtype = np.uint8 if all(m.num_bin <= 256 for m in self.bin_mappers) \
            else np.uint16
        out = np.empty((self.num_data, f_used), dtype=dtype)
        for f in range(f_used):
            codes = self.bins[:, bt.group_of[f]].astype(np.int32)
            rel = codes - bt.offset_of[f]
            inband = (rel >= 0) & (rel < bt.nslots_of[f])
            dec = rel + (rel >= bt.skip_of[f])
            out[:, f] = np.where(inband, dec, bt.skip_of[f]).astype(dtype)
        return out

    def debundle(self) -> None:
        """Replace the bundled bin matrix with the per-feature view
        (consumers that shard by feature — parallel learners — keep their
        simple layout; the reference supports EFB there via FeatureGroup
        indirection, which is a later-round TPU design)."""
        if self.efb_trivial:
            return
        self.bins = self.feature_bins()
        self.bundles = None
        self._device_bins = None
        self._measure_occupancy()  # stats follow the layout change

    # ------------------------------------------------------------------
    @staticmethod
    def _find_bin_mappers_local(sample_col_nonzeros, total_features: int,
                                sample_cnt: int, config: Config,
                                cat_set) -> List["BinMapper"]:
        """Single-machine per-feature bin finding
        (DatasetLoader::ConstructBinMappers, dataset_loader.cpp:527)."""
        mappers: List[BinMapper] = []
        for f in range(total_features):
            _, col = sample_col_nonzeros(f)
            nonzero = col[(np.abs(col) > K_ZERO_THRESHOLD) | np.isnan(col)]
            m = BinMapper()
            if config.max_bin_by_feature and f < len(config.max_bin_by_feature):
                mb = config.max_bin_by_feature[f]
            else:
                mb = config.max_bin
            m.find_bin(nonzero, sample_cnt, mb,
                       min_data_in_bin=config.min_data_in_bin,
                       min_split_data=config.min_data_in_leaf,
                       pre_filter=config.feature_pre_filter,
                       bin_type=(BIN_CATEGORICAL if f in cat_set
                                 else BIN_NUMERICAL),
                       use_missing=config.use_missing,
                       zero_as_missing=config.zero_as_missing)
            mappers.append(m)
        return mappers

    @classmethod
    def from_matrix(cls, data: np.ndarray, config: Config,
                    label: Optional[np.ndarray] = None,
                    weight: Optional[np.ndarray] = None,
                    group: Optional[np.ndarray] = None,
                    init_score: Optional[np.ndarray] = None,
                    feature_names: Optional[Sequence[str]] = None,
                    categorical_feature: Optional[Sequence[int]] = None,
                    reference: Optional["BinnedDataset"] = None) -> "BinnedDataset":
        """Construct from a raw row-major matrix.

        Mirrors LGBM_DatasetCreateFromMat -> DatasetLoader::ConstructFromSampleData
        (reference src/c_api.cpp, src/io/dataset_loader.cpp:527): sample rows,
        find bins per feature, then push all rows through the mappers.
        ``reference`` aligns bin mappers with a previously-constructed dataset
        (validation data; reference Dataset::CreateValid, dataset.cpp).
        """
        sparse_input = _is_sparse(data)
        data_csr = None
        if sparse_input:
            import scipy.sparse as sp
            # keep the CSR form (when that is what arrived) for the
            # row-sampling step below: re-deriving CSR from the CSC of
            # a multi-billion-nnz matrix is a second full sort + copy
            if sp.isspmatrix_csr(data):
                data_csr = data
            data = data.tocsc() if not sp.isspmatrix_csc(data) else data
        else:
            data = np.asarray(data)
            if data.ndim != 2:
                log.fatal("Data must be 2-dimensional")
        n, total_features = data.shape
        ds = cls()
        ds.num_data = n
        ds.num_total_features = total_features
        ds.metadata = Metadata(n)
        ds.metadata.set_label(label)
        ds.metadata.set_weights(weight)
        ds.metadata.set_query(group)
        ds.metadata.set_init_score(init_score)
        ds.max_bin = config.max_bin

        if feature_names is None:
            feature_names = [f"Column_{i}" for i in range(total_features)]
        ds.feature_names = list(feature_names)

        if reference is not None:
            # validation set: reuse the reference's mappers AND bundles
            # (scores are updated by bin-space traversal, which decodes
            # through the training set's bundle tables)
            ds.bin_mappers = reference.bin_mappers
            ds.real_feature_index = reference.real_feature_index
            ds.inner_feature_index = reference.inner_feature_index
            ds.feature_names = reference.feature_names
            ds.max_bin = reference.max_bin
            ds._monotone_constraints = reference._monotone_constraints
            ds.bundles = reference.bundles
            ds._apply_mappers(data)
            return ds

        if categorical_feature is None:
            categorical_feature = _parse_categorical(config.categorical_feature,
                                                     ds.feature_names)
        cat_set = set(categorical_feature or [])

        # --- sampling for bin finding (dataset_loader.cpp:120-165) ---
        sample_cnt = min(config.bin_construct_sample_cnt, n)
        rng = np.random.RandomState(config.data_random_seed)
        if sample_cnt < n:
            sample_idx = np.sort(rng.choice(n, size=sample_cnt, replace=False))
            if sparse_input:
                rows = data_csr if data_csr is not None else data.tocsr()
                sample = rows[sample_idx].tocsc()
            else:
                sample = data[sample_idx]
        else:
            sample = data
        if not sparse_input:
            sample = np.asarray(sample, dtype=np.float64)

        def sample_col_nonzeros(f):
            """(row_indices, values) of the sample column's stored
            entries — full column for dense input."""
            if sparse_input:
                idx, vals = _csc_col(sample, f)
                return idx, np.asarray(vals, dtype=np.float64)
            col = sample[:, f]
            return np.arange(sample_cnt), col

        # --- per-feature bin finding ---
        if config.num_machines > 1:
            # distributed construction protocol: per-rank owned-feature
            # binning + mapper allgather over the mesh (reference
            # dataset_loader.cpp:917-990). Single-controller mode bins
            # over the full in-process sample, so boundaries are
            # bit-identical to single-machine construction. Sparse
            # samples stay CSC end-to-end (round-5: the dense-only
            # restriction is gone — column slices come from the CSC
            # structure inside find_bins_for_features)
            from .distributed import distributed_find_bin_mappers
            mappers = distributed_find_bin_mappers(
                sample if sparse_input
                else np.asarray(sample, dtype=np.float64),
                config, cat_set)
        else:
            mappers = cls._find_bin_mappers_local(
                sample_col_nonzeros, total_features, sample_cnt, config,
                cat_set)

        used = [f for f in range(total_features) if not mappers[f].is_trivial]
        if not used:
            log.warning("There are no meaningful features, as all feature values are constant.")
        ds.bin_mappers = [mappers[f] for f in used]
        ds.real_feature_index = used
        ds.inner_feature_index = {f: i for i, f in enumerate(used)}
        if config.monotone_constraints:
            ds._monotone_constraints = [
                config.monotone_constraints[f] if f < len(config.monotone_constraints) else 0
                for f in used]

        # --- EFB bundling decision over the sample (dataset.cpp:50-302) ---
        if config.enable_bundle and len(used) > 1:
            from .efb import bundle_eligible
            nonzero_rows: List[np.ndarray] = []
            bundle_ok: List[bool] = []
            empty = np.empty(0, dtype=np.int64)
            for i, f in enumerate(used):
                m = ds.bin_mappers[i]
                ok = bundle_eligible(m) and m.sparse_rate >= 0.5
                bundle_ok.append(ok)
                if not ok:
                    nonzero_rows.append(empty)
                    continue
                idx, vals = sample_col_nonzeros(f)
                b = m.values_to_bins(vals)
                nonzero_rows.append(np.asarray(idx)[b != m.most_freq_bin])
            ds.bundles = build_bundles(
                nonzero_rows, ds.bin_mappers, sample_cnt, True,
                bundle_ok=bundle_ok,
                max_bundle_bins=config.efb_max_bundle_bins,
                max_conflict_rate=config.efb_max_conflict_rate)
            if ds.bundles.is_trivial:
                ds.bundles = None
        ds._apply_mappers(data)
        return ds

    def _apply_mappers(self, data: np.ndarray) -> None:
        """Push every row through the mappers into the packed bin-code
        matrix: [N, F_used] per-feature codes when bundling is trivial,
        [N, num_groups] bundle codes otherwise (reference
        FeatureGroup::PushData / Bin::Push; sparse inputs touch only
        their stored entries — never densified)."""
        n = data.shape[0]
        sparse = _is_sparse(data)
        mappers = self.bin_mappers
        bt = self.bundles

        def col_bins(i: int):
            """(row_indices_or_None, codes) for used feature i; None row
            indices mean 'all rows, in order'."""
            f = self.real_feature_index[i]
            if sparse:
                idx, vals = _csc_col(data, f)
                vals = np.asarray(vals, dtype=np.float64)
                _reject_inf_feature(vals, self.feature_names, f)
                return idx, mappers[i].values_to_bins(vals)
            col = np.asarray(data[:, f], dtype=np.float64)
            _reject_inf_feature(col, self.feature_names, f)
            return None, mappers[i].values_to_bins(col)

        if bt is None or bt.is_trivial:
            f_used = len(mappers)
            dtype = np.uint8 if all(m.num_bin <= 256 for m in mappers) \
                else np.uint16
            bins = np.empty((n, f_used), dtype=dtype)
            for i in range(f_used):
                idx, codes = col_bins(i)
                if idx is None:
                    bins[:, i] = codes.astype(dtype)
                else:
                    zero_bin = mappers[i].value_to_bin(0.0)
                    bins[:, i] = dtype(zero_bin)
                    bins[idx, i] = codes.astype(dtype)
        else:
            dtype = np.uint8 if int(bt.group_num_bins.max()) <= 256 \
                else np.uint16
            bins = np.empty((n, bt.num_groups), dtype=dtype)
            for g, members in enumerate(bt.groups):
                if len(members) == 1:
                    i = members[0]
                    idx, codes = col_bins(i)
                    if idx is None:
                        bins[:, g] = codes.astype(dtype)
                    else:
                        bins[:, g] = dtype(mappers[i].value_to_bin(0.0))
                        bins[idx, g] = codes.astype(dtype)
                else:
                    # shared column: code 0 = every member at its
                    # most-frequent bin; later members overwrite on the
                    # (conflict-budgeted) overlapping rows
                    code = np.zeros(n, dtype=dtype)
                    for i in members:
                        idx, codes = col_bins(i)
                        mfb = bt.skip_of[i]
                        keep = codes != mfb
                        rows = np.flatnonzero(keep) if idx is None else idx[keep]
                        b = codes[keep]
                        slot = b - (b > mfb)
                        code[rows] = (bt.offset_of[i] + slot).astype(dtype)
                    bins[:, g] = code
        self.bins = bins
        self.num_data = n
        self._measure_occupancy()

    def _measure_occupancy(self) -> None:
        """Record construct-time row-occupancy statistics (mean/max
        present codes per row, per-group density, sampled default
        codes) for the planar-vs-multival histogram layout decision —
        ops/histogram.py hist_layout(). Sampled and cheap; runs on
        every construction path (from_matrix, create_valid reference,
        load_binary) so the stats always match the current bin
        matrix."""
        self.occupancy = None
        if self.bins is None or self.bins.size == 0:
            return
        from ..ops.multival import measure_occupancy
        self.occupancy = measure_occupancy(self.bins)

    # ------------------------------------------------------------------
    def create_valid(self, data: np.ndarray, label=None, weight=None,
                     group=None, init_score=None) -> "BinnedDataset":
        ds = BinnedDataset.from_matrix(
            data, Config(), label=label, weight=weight, group=group,
            init_score=init_score, reference=self)
        return ds

    def monotone_constraint(self, inner_feature: int) -> int:
        if not self._monotone_constraints:
            return 0
        return self._monotone_constraints[inner_feature]

    def trace_signature(self) -> "tuple[str, bool]":
        """(digest, shareable) identity of everything dataset-derived
        that shapes a traced learner program.

        Bin boundary VALUES deliberately do not enter: traced programs
        operate on bin codes and route on bin-index thresholds, so two
        datasets with identical mapper *structure* (per-feature num_bin
        / missing_type / default_bin / bin_type), identical monotone
        constraints, and identical EFB bundle tables trace byte-
        identical programs — letting same-shaped learners share one
        compiled executable (compile/manager.py shared_entry).

        EFB table CONTENTS are hashed (not just shape) because learners
        close over the device copies; two different bundlings must not
        alias one program.

        On any failure the fallback is a per-instance uid: sharing is
        lost, correctness kept — callers should then register their
        entries with store=False so uid keys never pollute the on-disk
        AOT store."""
        if getattr(self, "_trace_sig", None) is None:
            import hashlib
            try:
                h = hashlib.sha256()
                for m in self.bin_mappers:
                    h.update(("%d,%d,%d,%d;" % (
                        m.num_bin, m.missing_type, m.default_bin,
                        m.bin_type)).encode())
                h.update(np.asarray(self._monotone_constraints or [],
                                    np.int32).tobytes())
                bt = self.bundles
                if bt is not None and not bt.is_trivial:
                    for a in (bt.group_of, bt.offset_of, bt.nslots_of,
                              bt.skip_of, bt.group_num_bins):
                        h.update(np.ascontiguousarray(a).tobytes())
                occ = self.occupancy
                if occ is not None:
                    # DERIVED discrete occupancy values only (never the
                    # raw float stats — jittery means must not fracture
                    # the AOT key space): the bucketed row capacity
                    # shapes the multival planes, the wide-sparse bool
                    # is the auto layout decision, and the sampled
                    # default codes are closed over by serial multival
                    # entries (ops/multival.py group tables)
                    from ..ops.multival import (
                        bucket_row_capacity, MULTIVAL_MIN_GROUPS,
                        MULTIVAL_MAX_OCCUPANCY)
                    wide = (occ.num_groups >= MULTIVAL_MIN_GROUPS
                            and occ.row_nnz_mean
                            <= MULTIVAL_MAX_OCCUPANCY * occ.num_groups)
                    h.update(("mv:%d,%d;" % (
                        bucket_row_capacity(occ.row_nnz_max),
                        int(wide))).encode())
                    h.update(np.ascontiguousarray(
                        occ.default_code).tobytes())
                self._trace_sig = ("ds-" + h.hexdigest()[:20], True)
            except Exception:
                self._trace_sig = ("uid-%x" % id(self), False)
        return self._trace_sig

    # --- binary cache (reference Dataset::SaveBinaryFile, dataset.cpp:890) ---
    def save_binary(self, filename: str) -> None:
        header = {
            "num_data": self.num_data,
            "num_total_features": self.num_total_features,
            "real_feature_index": self.real_feature_index,
            "feature_names": self.feature_names,
            "max_bin": self.max_bin,
            "monotone_constraints": self._monotone_constraints,
            "bin_mappers": [m.to_dict() for m in self.bin_mappers],
            "bundle_groups": None if self.efb_trivial else self.bundles.groups,
            "bins_dtype": str(self.bins.dtype),
            "has_label": self.metadata.label is not None,
            "has_weights": self.metadata.weights is not None,
            "has_query": self.metadata.query_boundaries is not None,
            "has_init_score": self.metadata.init_score is not None,
        }
        with open(filename, "wb") as fh:
            hdr = json.dumps(header).encode()
            fh.write(b"LGTPU1\n")
            fh.write(len(hdr).to_bytes(8, "little"))
            fh.write(hdr)
            fh.write(self.bins.tobytes())
            if self.metadata.label is not None:
                fh.write(self.metadata.label.astype(np.float32).tobytes())
            if self.metadata.weights is not None:
                fh.write(self.metadata.weights.astype(np.float32).tobytes())
            if self.metadata.query_boundaries is not None:
                qb = self.metadata.query_boundaries.astype(np.int32)
                fh.write(len(qb).to_bytes(8, "little"))
                fh.write(qb.tobytes())
            if self.metadata.init_score is not None:
                isc = self.metadata.init_score.astype(np.float64)
                fh.write(len(isc).to_bytes(8, "little"))
                fh.write(isc.tobytes())

    @classmethod
    def load_binary(cls, filename: str) -> "BinnedDataset":
        with open(filename, "rb") as fh:
            magic = fh.readline()
            if magic != b"LGTPU1\n":
                log.fatal("%s is not a lightgbm_tpu binary dataset file", filename)
            hdr_len = int.from_bytes(fh.read(8), "little")
            header = json.loads(fh.read(hdr_len).decode())
            ds = cls()
            ds.num_data = header["num_data"]
            ds.num_total_features = header["num_total_features"]
            ds.real_feature_index = list(header["real_feature_index"])
            ds.inner_feature_index = {f: i for i, f in enumerate(ds.real_feature_index)}
            ds.feature_names = list(header["feature_names"])
            ds.max_bin = header["max_bin"]
            ds._monotone_constraints = list(header["monotone_constraints"])
            ds.bin_mappers = [BinMapper.from_dict(d) for d in header["bin_mappers"]]
            groups = header.get("bundle_groups")
            if groups:
                ds.bundles = BundleTables(
                    [list(g) for g in groups],
                    [m.num_bin for m in ds.bin_mappers],
                    [m.most_freq_bin for m in ds.bin_mappers])
            dtype = np.dtype(header["bins_dtype"])
            n, f = ds.num_data, len(ds.bin_mappers) if not groups else len(groups)
            ds.bins = np.frombuffer(fh.read(n * f * dtype.itemsize), dtype=dtype).reshape(n, f).copy()
            ds.metadata = Metadata(n)
            if header["has_label"]:
                ds.metadata.label = np.frombuffer(fh.read(4 * n), dtype=np.float32).copy()
            if header["has_weights"]:
                ds.metadata.weights = np.frombuffer(fh.read(4 * n), dtype=np.float32).copy()
            if header["has_query"]:
                qn = int.from_bytes(fh.read(8), "little")
                ds.metadata.query_boundaries = np.frombuffer(fh.read(4 * qn), dtype=np.int32).copy()
            if header["has_init_score"]:
                sn = int.from_bytes(fh.read(8), "little")
                ds.metadata.init_score = np.frombuffer(fh.read(8 * sn), dtype=np.float64).copy()
        ds._measure_occupancy()
        return ds


def _parse_categorical(spec: Union[str, List[int], List[str], None],
                       feature_names: Sequence[str]) -> List[int]:
    """Resolve Config.categorical_feature (indices, names, or 'name:a,b' /
    '0,1,2' strings; reference config.h categorical_feature doc) to column
    indices."""
    if spec is None:
        return []
    if isinstance(spec, str):
        s = spec.strip()
        if not s:
            return []
        items: List[Any] = [x for x in (s[5:] if s.startswith("name:") else s).split(",") if x]
    else:
        items = list(spec)
    out: List[int] = []
    name_index = {nm: i for i, nm in enumerate(feature_names)}
    for it in items:
        if isinstance(it, str) and not it.lstrip("-").isdigit():
            if it in name_index:
                out.append(name_index[it])
            else:
                log.warning("Unknown categorical feature name %s, ignored", it)
        else:
            out.append(int(it))
    return out
