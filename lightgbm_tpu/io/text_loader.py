"""Text data loading (CSV/TSV/LibSVM with auto-detection).

Host-side equivalent of the reference parser stack (reference:
src/io/parser.cpp:262 CreateParser with format auto-detection by line
inspection, src/io/parser.hpp CSVParser:18 / TSVParser:55 /
LibSVMParser:91, and DatasetLoader label/weight/group column handling,
src/io/dataset_loader.cpp:167). Parsing feeds the binner once at load
time, so numpy-vectorized host parsing is the right tool; a C++
fast-path parser is only warranted if profiling shows load-bound
workloads (SURVEY §7 design stance).
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..config import Config
from ..utils import log


def _detect_format(line: str) -> str:
    """reference Parser::CreateParser line inspection."""
    if "\t" in line:
        tokens = line.strip().split("\t")
        if any(":" in t for t in tokens[1:]):
            return "libsvm"
        return "tsv"
    if "," in line:
        return "csv"
    tokens = line.strip().split()
    if any(":" in t for t in tokens[1:]):
        return "libsvm"
    return "csv"


def _parse_column_spec(spec: str, header_names, default: int = -1) -> int:
    if spec == "":
        return default
    if spec.startswith("name:"):
        name = spec[5:]
        if header_names and name in header_names:
            return header_names.index(name)
        log.fatal("Could not find column %s in data file", name)
    return int(spec)


def load_text_file(path: str, config: Config):
    """Returns (matrix, label, weight, group)."""
    with open(path) as fh:
        first = fh.readline()
    fmt = _detect_format(first)

    header_names = None
    skip = 0
    if config.header:
        header_names = [t.strip() for t in
                        first.strip().replace("\t", ",").split(",")]
        skip = 1

    if fmt == "libsvm":
        mat, label = _load_libsvm(path, skip)
        weight = None
    else:
        delim = "\t" if fmt == "tsv" else ","
        raw = np.genfromtxt(path, delimiter=delim, skip_header=skip,
                            dtype=np.float64)
        if raw.ndim == 1:
            raw = raw.reshape(-1, 1)
        label_col = _parse_column_spec(config.label_column, header_names, 0)
        weight_col = _parse_column_spec(config.weight_column, header_names)
        group_col = _parse_column_spec(config.group_column, header_names)
        cols = [c for c in range(raw.shape[1])
                if c not in (label_col, weight_col, group_col)]
        label = raw[:, label_col] if label_col >= 0 else None
        weight = raw[:, weight_col] if weight_col >= 0 else None
        mat = raw[:, cols]

    group = None
    qpath = path + ".query"
    if os.path.exists(qpath):
        group = np.loadtxt(qpath, dtype=np.int64).reshape(-1)
    wpath = path + ".weight"
    if os.path.exists(wpath):
        weight = np.loadtxt(wpath, dtype=np.float64).reshape(-1)
    ipath = path + ".init"
    init = None
    if os.path.exists(ipath):
        init = np.loadtxt(ipath, dtype=np.float64).reshape(-1)
    if init is not None:
        return mat, label, weight, group  # init handled by caller if needed
    return mat, label, weight, group


def _load_libsvm(path: str, skip: int) -> Tuple[np.ndarray, np.ndarray]:
    labels = []
    rows = []
    max_feat = -1
    with open(path) as fh:
        for i, line in enumerate(fh):
            if i < skip:
                continue
            toks = line.strip().split()
            if not toks:
                continue
            labels.append(float(toks[0]))
            feats = {}
            for t in toks[1:]:
                if ":" not in t:
                    continue
                k, v = t.split(":", 1)
                k = int(k)
                feats[k] = float(v)
                max_feat = max(max_feat, k)
            rows.append(feats)
    mat = np.zeros((len(rows), max_feat + 1), dtype=np.float64)
    for i, feats in enumerate(rows):
        for k, v in feats.items():
            mat[i, k] = v
    return mat, np.asarray(labels)
