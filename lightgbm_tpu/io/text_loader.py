"""Text data loading (CSV/TSV/LibSVM with auto-detection).

Host-side equivalent of the reference parser stack (reference:
src/io/parser.cpp:262 CreateParser with format auto-detection by line
inspection, src/io/parser.hpp CSVParser:18 / TSVParser:55 /
LibSVMParser:91, and DatasetLoader label/weight/group/ignore column
handling, src/io/dataset_loader.cpp:167-260). Robustness mirrors the
reference's Atof/field handling: quoted fields, NA strings ("na",
"nan", "null", "none", empty), name:-addressed columns against the
header, inf values. CSV/TSV rides pandas' C parser (the host-side
equivalent of the reference's hand-rolled C++ parser); LibSVM parses
to scipy CSR so sparse files feed the EFB data plane without
densifying.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..utils import log

NA_STRINGS = ["", "na", "nan", "null", "none", "n/a", "NA", "NaN", "NAN",
              "Null", "NULL", "None", "NONE", "N/A", "?"]


def _detect_format(lines: List[str]) -> str:
    """reference Parser::CreateParser line inspection (parser.cpp:262):
    colon-separated index:value tokens mean LibSVM; else the delimiter
    with the most columns wins."""
    sample = [ln for ln in lines if ln.strip()]
    if not sample:
        return "csv"

    def libsvm_verdict(ln: str):
        """True / False / None (a bare label line is compatible with
        LibSVM — rows can be all-default — but is no evidence)."""
        toks = ln.replace("\t", " ").split()
        if len(toks) == 1:
            try:
                float(toks[0])
                return None
            except ValueError:
                return False
        pairs = [t for t in toks[1:] if ":" in t]
        ok = 0
        for t in pairs:
            k, _, v = t.partition(":")
            try:
                int(k), float(v)
                ok += 1
            except ValueError:
                return False
        return ok > 0

    verdicts = [libsvm_verdict(ln) for ln in sample]
    if any(v is True for v in verdicts) and not any(v is False
                                                    for v in verdicts):
        return "libsvm"
    tabs = sample[-1].count("\t")
    commas = sample[-1].count(",")
    return "tsv" if tabs >= commas and tabs > 0 else "csv"


def _resolve_column(spec: str, header_names: Optional[Sequence[str]],
                    default: int = -1, what: str = "column",
                    label_col: Optional[int] = None) -> int:
    """label_column/weight_column/group_column spec -> raw file column
    (reference config.h: int index or 'name:<column>'). Integer specs
    for non-label columns do NOT count the label column (reference
    parser semantics / docs: 'it doesn't count the label column'), so
    they shift past it; name: specs address the file directly."""
    if spec == "":
        return default
    if spec.startswith("name:"):
        name = spec[5:]
        if header_names and name in header_names:
            return list(header_names).index(name)
        log.fatal("Could not find %s %s in data file header", what, name)
    try:
        idx = int(spec)
    except ValueError:
        log.fatal("Invalid %s specifier %r (use an index or name:<col>)",
                  what, spec)
        return default
    if label_col is not None and idx >= label_col >= 0:
        idx += 1
    return idx


def _resolve_ignore(spec: str, header_names,
                    label_col: Optional[int] = None) -> List[int]:
    """Comma list of ignore columns through the same resolution as the
    single-column specs (missing names are fatal, like the reference's
    DatasetLoader ignore handling; int indices don't count the label)."""
    if not spec:
        return []
    named = spec.startswith("name:")
    items = (spec[5:] if named else spec).split(",")
    return [_resolve_column("name:" + it.strip() if named else it.strip(),
                            header_names, -1, "ignore_column", label_col)
            for it in items if it.strip()]


def _group_sizes_from_query_ids(qids: np.ndarray) -> np.ndarray:
    """A query-id column becomes per-query sizes: consecutive equal ids
    form one group (reference metadata.cpp query handling)."""
    if len(qids) == 0:
        return np.zeros(0, dtype=np.int64)
    change = np.flatnonzero(np.diff(qids) != 0)
    bounds = np.concatenate([[-1], change, [len(qids) - 1]])
    return np.diff(bounds).astype(np.int64)


def load_text_file(path: str, config: Config):
    """Returns (matrix, label, weight, group, init_score); matrix is
    dense ndarray for CSV/TSV, scipy CSR for LibSVM (when scipy is
    available)."""
    with open(path) as fh:
        head = [fh.readline() for _ in range(3)]
    fmt = _detect_format(head)

    header_names = None
    skip = 0
    if config.header:
        delim = "\t" if fmt != "csv" else ","
        header_names = [t.strip().strip('"') for t in
                        head[0].strip().split(delim)]
        skip = 1

    if fmt == "libsvm":
        mat, label = _load_libsvm(path, skip)
        weight = None
        group = None
    else:
        delim = "\t" if fmt == "tsv" else ","
        try:
            import pandas as pd
            df = pd.read_csv(path, sep=delim, header=None, skiprows=skip,
                             na_values=NA_STRINGS, keep_default_na=True,
                             quotechar='"', skip_blank_lines=True,
                             comment=None)
            raw = np.empty(df.shape, dtype=np.float64)
            for i, col in enumerate(df.columns):
                raw[:, i] = pd.to_numeric(df[col], errors="coerce")
            n_bad = int(np.all(np.isnan(raw), axis=0).sum())
            if n_bad == raw.shape[1] and raw.size:
                log.fatal("Could not parse any numeric column from %s "
                          "(wrong delimiter or header=true missing?)", path)
        except ImportError:
            raw = _parse_delimited_fallback(path, delim, skip)
        if raw.ndim == 1:
            raw = raw.reshape(-1, 1)
        label_col = _resolve_column(config.label_column, header_names, 0,
                                    "label_column")
        weight_col = _resolve_column(config.weight_column, header_names,
                                     -1, "weight_column", label_col)
        group_col = _resolve_column(config.group_column, header_names,
                                    -1, "group_column", label_col)
        drop = set(_resolve_ignore(config.ignore_column, header_names,
                                   label_col))
        drop.update(c for c in (label_col, weight_col, group_col) if c >= 0)
        cols = [c for c in range(raw.shape[1]) if c not in drop]
        label = raw[:, label_col] if label_col >= 0 else None
        weight = raw[:, weight_col] if weight_col >= 0 else None
        group = (_group_sizes_from_query_ids(raw[:, group_col])
                 if group_col >= 0 else None)
        mat = raw[:, cols]

    # sidecar files override inline columns (reference
    # dataset_loader.cpp LoadQueryBoundaries / SetWeights)
    qpath = path + ".query"
    if os.path.exists(qpath):
        group = np.loadtxt(qpath, dtype=np.int64).reshape(-1)
    wpath = path + ".weight"
    if os.path.exists(wpath):
        weight = np.loadtxt(wpath, dtype=np.float64).reshape(-1)
    # initial scores: "<data>.init" (or the initscore_filename override,
    # reference config "initscore_filename"), one row per data row, one
    # column per class (reference metadata.cpp:389-430 LoadInitialScore;
    # class-major flattening like Metadata::init_score_)
    init_score = None
    ipath = config.initscore_filename or (path + ".init")
    if os.path.exists(ipath):
        isc = np.loadtxt(ipath, dtype=np.float64, ndmin=2)
        init_score = isc.T.reshape(-1)  # [num_class * n], class-major
        log.info("Loading initial scores...")
    return mat, label, weight, group, init_score


def _parse_delimited_fallback(path: str, delim: str, skip: int) -> np.ndarray:
    """csv-module fallback (quoted fields + NA strings) when pandas is
    unavailable."""
    import csv

    na = set(s.lower() for s in NA_STRINGS)
    rows = []
    with open(path, newline="") as fh:
        reader = csv.reader(fh, delimiter=delim, quotechar='"')
        for i, rec in enumerate(reader):
            if i < skip or not rec:
                continue
            vals = []
            for tok in rec:
                t = tok.strip()
                if t.lower() in na:
                    vals.append(np.nan)
                    continue
                try:
                    vals.append(float(t))
                except ValueError:
                    vals.append(np.nan)
            rows.append(vals)
    width = max((len(r) for r in rows), default=0)
    mat = np.full((len(rows), width), np.nan)
    for i, r in enumerate(rows):
        mat[i, :len(r)] = r
    return mat


def _load_libsvm(path: str, skip: int):
    """LibSVM '<label> <idx>:<val> ...' -> (CSR matrix, labels); rows
    with malformed pairs fail loudly with the line number (reference
    parser.hpp LibSVMParser)."""
    labels = []
    data, indices, indptr = [], [], [0]
    max_feat = -1
    with open(path) as fh:
        for i, line in enumerate(fh):
            if i < skip:
                continue
            toks = line.strip().split()
            if not toks:
                continue
            try:
                labels.append(float(toks[0]))
            except ValueError:
                log.fatal("Line %d of %s: bad label %r", i + 1, path,
                          toks[0])
            for t in toks[1:]:
                if ":" not in t:
                    continue
                k, _, v = t.partition(":")
                try:
                    k = int(k)
                    val = float(v)
                except ValueError:
                    log.fatal("Line %d of %s: bad feature pair %r",
                              i + 1, path, t)
                indices.append(k)
                data.append(val)
                max_feat = max(max_feat, k)
            indptr.append(len(data))
    try:
        import scipy.sparse as sp
        mat = sp.csr_matrix(
            (np.asarray(data, dtype=np.float64),
             np.asarray(indices, dtype=np.int64),
             np.asarray(indptr, dtype=np.int64)),
            shape=(len(labels), max_feat + 1))
    except ImportError:
        mat = np.zeros((len(labels), max_feat + 1), dtype=np.float64)
        for r in range(len(labels)):
            s, e = indptr[r], indptr[r + 1]
            mat[r, indices[s:e]] = data[s:e]
    return mat, np.asarray(labels)
