"""Quantile feature binning.

Behavioral re-implementation (host-side, numpy) of the reference BinMapper
(reference: src/io/bin.cpp — GreedyFindBin at bin.cpp:78,
FindBinWithZeroAsOneBin at bin.cpp:256, BinMapper::FindBin at bin.cpp:325;
ValueToBin at include/LightGBM/bin.h:457-495).  Binning runs once per feature
at Dataset construction time on a bounded sample (bin_construct_sample_cnt),
so it stays on the host; the resulting integer bin codes are what live on the
TPU.  Bin *application* (value->bin for the full column) is vectorized with
``np.searchsorted`` instead of the reference's per-value binary search.
"""
from __future__ import annotations

import math
from typing import Dict, List, Sequence

import numpy as np

from ..utils import log

K_ZERO_THRESHOLD = 1e-35
K_SPARSE_THRESHOLD = 0.8
K_EPSILON = 1e-15

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_NUMERICAL = 0
BIN_CATEGORICAL = 1

_MISSING_NAMES = {MISSING_NONE: "none", MISSING_ZERO: "zero", MISSING_NAN: "nan"}
_MISSING_FROM_NAME = {v: k for k, v in _MISSING_NAMES.items()}


def _next_after_up(x: float) -> float:
    """float64 nextafter toward +inf (reference Common::GetDoubleUpperBound)."""
    return float(np.nextafter(np.float64(x), np.inf))


def _check_double_equal_ordered(a: float, b: float) -> bool:
    """b <= nextafter(a, inf) (reference Common::CheckDoubleEqualOrdered)."""
    return b <= _next_after_up(a)


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                    max_bin: int, total_cnt: int,
                    min_data_in_bin: int) -> List[float]:
    """Equal-count greedy bin boundary search (reference bin.cpp:78-155).

    Returns the list of bin upper bounds; the last bound is +inf.
    """
    assert max_bin > 0
    if len(distinct_values) > 256:  # native pays off past trivial sizes
        from ..native import greedy_find_bin_native
        out = greedy_find_bin_native(distinct_values, counts, max_bin,
                                     total_cnt, min_data_in_bin)
        if out is not None:
            return out
    num_distinct = len(distinct_values)
    bin_upper_bound: List[float] = []
    if num_distinct <= max_bin:
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += int(counts[i])
            if cur_cnt >= min_data_in_bin:
                val = _next_after_up((float(distinct_values[i]) + float(distinct_values[i + 1])) / 2.0)
                if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
                    bin_upper_bound.append(val)
                    cur_cnt = 0
        bin_upper_bound.append(math.inf)
        return bin_upper_bound

    if min_data_in_bin > 0:
        max_bin = min(max_bin, max(1, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    rest_bin_cnt = max_bin
    rest_sample_cnt = total_cnt
    is_big = counts >= mean_bin_size
    rest_bin_cnt -= int(is_big.sum())
    rest_sample_cnt -= int(counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    upper_bounds = [math.inf] * max_bin
    lower_bounds = [math.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = float(distinct_values[0])
    cur_cnt = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur_cnt += int(counts[i])
        if (is_big[i] or cur_cnt >= mean_bin_size or
                (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = float(distinct_values[i])
            bin_cnt += 1
            lower_bounds[bin_cnt] = float(distinct_values[i + 1])
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _next_after_up((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
        if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
            bin_upper_bound.append(val)
    bin_upper_bound.append(math.inf)
    return bin_upper_bound


def find_bin_with_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                                  max_bin: int, total_sample_cnt: int,
                                  min_data_in_bin: int) -> List[float]:
    """Reserve a dedicated bin for ~zero values (reference bin.cpp:256-321).

    Negative values are binned on the left of the zero bin, positives on the
    right, with the per-side bin budget proportional to the side's data count.
    """
    dv = np.asarray(distinct_values, dtype=np.float64)
    cnts = np.asarray(counts, dtype=np.int64)
    left_mask = dv <= -K_ZERO_THRESHOLD
    right_mask = dv > K_ZERO_THRESHOLD
    zero_mask = ~left_mask & ~right_mask
    left_cnt_data = int(cnts[left_mask].sum())
    cnt_zero = int(cnts[zero_mask].sum())
    right_cnt_data = int(cnts[right_mask].sum())

    left_cnt = int(np.argmax(~left_mask)) if (~left_mask).any() else len(dv)

    bin_upper_bound: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        denom = max(total_sample_cnt - cnt_zero, 1)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bin_upper_bound = greedy_find_bin(dv[:left_cnt], cnts[:left_cnt],
                                          left_max_bin, left_cnt_data,
                                          min_data_in_bin)
        if bin_upper_bound:
            bin_upper_bound[-1] = -K_ZERO_THRESHOLD

    right_start = -1
    for i in range(left_cnt, len(dv)):
        if dv[i] > K_ZERO_THRESHOLD:
            right_start = i
            break

    right_max_bin = max_bin - 1 - len(bin_upper_bound)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(dv[right_start:], cnts[right_start:],
                                       right_max_bin, right_cnt_data,
                                       min_data_in_bin)
        bin_upper_bound.append(K_ZERO_THRESHOLD)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(math.inf)
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


class BinMapper:
    """Maps one raw feature column to integer bins.

    Mirrors the reference BinMapper state: ``bin_upper_bound_`` for numerical
    features, ``categorical_2_bin_`` / ``bin_2_categorical_`` for categorical
    ones, plus missing handling, default/most-frequent bin tracking
    (reference include/LightGBM/bin.h:61-225).
    """

    def __init__(self) -> None:
        self.num_bin: int = 1
        self.missing_type: int = MISSING_NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_type: int = BIN_NUMERICAL
        self.bin_upper_bound: np.ndarray = np.array([math.inf])
        self.categorical_2_bin: Dict[int, int] = {}
        self.bin_2_categorical: List[int] = []
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0
        self.most_freq_bin: int = 0

    # ------------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int,
                 max_bin: int, min_data_in_bin: int = 3,
                 min_split_data: int = 20, pre_filter: bool = False,
                 bin_type: int = BIN_NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False) -> None:
        """Find bin boundaries from a sample of the column
        (reference BinMapper::FindBin, bin.cpp:325-521).

        ``values`` are the sampled *non-zero* values (the reference pushes
        only nonzeros plus an implied zero count); zero count is inferred as
        total_sample_cnt - len(values) - nan_count.
        """
        values = np.asarray(values, dtype=np.float64)
        nan_cnt = int(np.isnan(values).sum())
        values = values[~np.isnan(values)]

        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            self.missing_type = MISSING_NAN if nan_cnt > 0 else MISSING_NONE
        # NaNs only stay "missing" for the NaN missing type; otherwise the
        # reference folds them into the zero count (bin.cpp:329-352 keeps
        # na_cnt=0 outside the NaN branch)
        na_cnt = nan_cnt if self.missing_type == MISSING_NAN else 0

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - len(values) - na_cnt)

        # distinct values (vectorized run-merge: adjacent sorted values equal
        # under CheckDoubleEqualOrdered collapse into one, keeping the larger
        # value — reference bin.cpp:355-383) with the zero pseudo-value
        # injected in value order
        values = np.sort(values, kind="stable")
        if len(values) > 0:
            new_run = np.empty(len(values), dtype=bool)
            new_run[0] = True
            if len(values) > 1:
                new_run[1:] = values[1:] > np.nextafter(values[:-1], np.inf)
            run_starts = np.flatnonzero(new_run)
            run_ends = np.concatenate([run_starts[1:], [len(values)]])
            base_dv = values[run_ends - 1]  # use the larger value of each run
            base_cnt = (run_ends - run_starts).astype(np.int64)
        else:
            base_dv = np.empty(0, dtype=np.float64)
            base_cnt = np.empty(0, dtype=np.int64)

        if len(base_dv) == 0:
            dv = np.asarray([0.0])
            cnts = np.asarray([zero_cnt], dtype=np.int64)
        else:
            pos = int(np.searchsorted(base_dv, 0.0, side="left"))
            zero_present = pos < len(base_dv) and base_dv[pos] == 0.0
            if zero_present:
                insert = False
            elif pos == 0 or pos == len(base_dv):
                insert = zero_cnt > 0  # all-positive (front) / all-negative (back)
            else:
                insert = True  # straddles zero: middle insert is unconditional
            if insert:
                dv = np.insert(base_dv, pos, 0.0)
                cnts = np.insert(base_cnt, pos, zero_cnt)
            else:
                dv, cnts = base_dv, base_cnt
        self.min_val = float(dv[0]) if len(dv) else 0.0
        self.max_val = float(dv[-1]) if len(dv) else 0.0
        cnt_in_bin: List[int] = []

        if bin_type == BIN_NUMERICAL:
            if self.missing_type == MISSING_ZERO:
                bounds = find_bin_with_zero_as_one_bin(
                    dv, cnts, max_bin, total_sample_cnt, min_data_in_bin)
                if len(bounds) == 2:
                    self.missing_type = MISSING_NONE
            elif self.missing_type == MISSING_NONE:
                bounds = find_bin_with_zero_as_one_bin(
                    dv, cnts, max_bin, total_sample_cnt, min_data_in_bin)
            else:  # NaN: reserve last bin for missing
                bounds = find_bin_with_zero_as_one_bin(
                    dv, cnts, max_bin - 1, total_sample_cnt - na_cnt,
                    min_data_in_bin)
                bounds.append(math.nan)
            self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            # count per bin: first bound >= value (vectorized form of the
            # reference's sequential walk; NaN sentinel bound sorts last
            # and finite values never reach it)
            n_search = self.num_bin - (1 if self.missing_type == MISSING_NAN
                                       else 0)
            bin_of_dv = np.searchsorted(self.bin_upper_bound[:n_search], dv,
                                        side="left")
            cnt_in_bin = np.bincount(bin_of_dv, weights=cnts,
                                     minlength=self.num_bin).astype(np.int64)
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
            assert self.num_bin <= max_bin
        else:
            # categorical (reference bin.cpp:428-494)
            dv_int: List[int] = []
            cnts_int: List[int] = []
            for v, c in zip(dv, cnts):
                iv = int(v)
                if iv < 0:
                    na_cnt += int(c)
                    log.warning("Met negative value in categorical features, "
                                "will convert it to NaN")
                elif dv_int and iv == dv_int[-1]:
                    cnts_int[-1] += int(c)
                else:
                    dv_int.append(iv)
                    cnts_int.append(int(c))
            rest_cnt = total_sample_cnt - na_cnt
            if rest_cnt > 0:
                # stable sort by count desc
                order = sorted(range(len(dv_int)), key=lambda i: -cnts_int[i])
                cut_cnt = int(round((total_sample_cnt - na_cnt) * 0.99))
                distinct_cnt = len(dv_int) + (1 if na_cnt > 0 else 0)
                max_bin_c = min(distinct_cnt, max_bin)
                self.categorical_2_bin = {-1: 0}
                self.bin_2_categorical = [-1]
                cnt_in_bin = [0]
                self.num_bin = 1
                used_cnt = 0
                cur = 0
                while cur < len(order) and (used_cnt < cut_cnt or self.num_bin < max_bin_c):
                    idx = order[cur]
                    if cnts_int[idx] < min_data_in_bin and cur > 1:
                        break
                    self.bin_2_categorical.append(dv_int[idx])
                    self.categorical_2_bin[dv_int[idx]] = self.num_bin
                    used_cnt += cnts_int[idx]
                    cnt_in_bin.append(cnts_int[idx])
                    self.num_bin += 1
                    cur += 1
                if cur == len(order) and na_cnt == 0:
                    self.missing_type = MISSING_NONE
                else:
                    self.missing_type = MISSING_NAN
                cnt_in_bin[0] = int(total_sample_cnt - used_cnt)

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and pre_filter and _need_filter(
                cnt_in_bin, total_sample_cnt, min_split_data, bin_type):
            self.is_trivial = True

        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            max_sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
            if self.most_freq_bin != self.default_bin and max_sparse_rate < K_SPARSE_THRESHOLD:
                self.most_freq_bin = self.default_bin
            self.sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
        else:
            self.sparse_rate = 1.0

    # ------------------------------------------------------------------
    def value_to_bin(self, value: float) -> int:
        """Single value -> bin (reference bin.h:457-495)."""
        return int(self.values_to_bins(np.asarray([value]))[0])

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized column -> bin codes (replaces per-value binary search)."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BIN_NUMERICAL:
            nan_mask = np.isnan(values)
            # non-NaN-missing-type: NaN treated as 0.0 (reference bin.h:462-466)
            safe = np.where(nan_mask, 0.0, values)
            n_search = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
            if len(values) > 4096:
                from ..native import values_to_bins_native
                out = values_to_bins_native(safe,
                                            self.bin_upper_bound[:n_search])
                if out is not None:
                    out = out.astype(np.int64)
                    if self.missing_type == MISSING_NAN:
                        out = np.where(nan_mask, self.num_bin - 1, out)
                    return out
            # smallest j with value <= upper[j]; last searched bound is +inf
            out = np.searchsorted(self.bin_upper_bound[:n_search], safe, side="left")
            out = np.minimum(out, n_search - 1)
            if self.missing_type == MISSING_NAN:
                out = np.where(nan_mask, self.num_bin - 1, out)
            return out.astype(np.int32)
        else:
            iv = np.where(np.isnan(values), -1, values).astype(np.int64)
            out = np.zeros(len(values), dtype=np.int32)
            if self.categorical_2_bin:
                keys = np.fromiter(self.categorical_2_bin.keys(), dtype=np.int64)
                vals = np.fromiter(self.categorical_2_bin.values(), dtype=np.int64)
                order = np.argsort(keys)
                keys, vals = keys[order], vals[order]
                pos = np.searchsorted(keys, iv)
                pos = np.clip(pos, 0, len(keys) - 1)
                hit = keys[pos] == iv
                out = np.where(hit & (iv >= 0), vals[pos], 0).astype(np.int32)
            return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative split value for a bin boundary (used for model
        thresholds: reference stores bin_upper_bound_[bin] as the real
        threshold, tree.cpp RealThreshold)."""
        if self.bin_type == BIN_NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    # serialization ----------------------------------------------------
    def to_dict(self) -> dict:
        d = {
            "num_bin": self.num_bin,
            "missing_type": _MISSING_NAMES[self.missing_type],
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_type": "categorical" if self.bin_type == BIN_CATEGORICAL else "numerical",
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
        }
        if self.bin_type == BIN_NUMERICAL:
            d["bin_upper_bound"] = [float(x) for x in self.bin_upper_bound]
        else:
            d["bin_2_categorical"] = list(self.bin_2_categorical)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = _MISSING_FROM_NAME[d["missing_type"]]
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = BIN_CATEGORICAL if d["bin_type"] == "categorical" else BIN_NUMERICAL
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        m.most_freq_bin = int(d["most_freq_bin"])
        if m.bin_type == BIN_NUMERICAL:
            m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        else:
            m.bin_2_categorical = [int(x) for x in d["bin_2_categorical"]]
            m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        return m


def _need_filter(cnt_in_bin: Sequence[int], total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """True if no split on this feature could satisfy min_data constraints
    (reference bin.cpp:54-76)."""
    if bin_type == BIN_NUMERICAL:
        sum_left = 0
        for i in range(len(cnt_in_bin) - 1):
            sum_left += cnt_in_bin[i]
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return False
        return True
    if len(cnt_in_bin) <= 2:
        for i in range(len(cnt_in_bin) - 1):
            if cnt_in_bin[i] >= filter_cnt and total_cnt - cnt_in_bin[i] >= filter_cnt:
                return False
        return True
    return False
