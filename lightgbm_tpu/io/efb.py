"""Exclusive Feature Bundling — the sparse-feature data plane.

TPU re-design of the reference EFB (reference: src/io/dataset.cpp:50-302
GetConflictCount/FindGroups/FastFeatureBundling and FeatureGroup's
shared-column bin packing, include/LightGBM/feature_group.h:21). The
reference bundles near-mutually-exclusive sparse features into one
physical bin column so the histogram pass touches G << F columns; the
same packing here shrinks the HBM-resident bin matrix [N, G] and every
histogram/partition pass over it.

Encoding (one uint8/uint16 column per bundle):
  code 0                    = every member feature at its most-frequent
                              bin (for sparse features: the zero bin)
  code offset_f + slot(b)   = member f at bin b != mfb_f, where
                              slot(b) = b - (b > mfb_f) skips the mfb
                              slot (reference FeatureGroup bin offsets
                              skip the most-freq bin the same way)
Conflicts (two members non-default on one row) overwrite in member
order, bounded by the sampled conflict budget — identical information
loss to the reference's Push ordering (dataset.cpp:297 comment).

The per-feature histogram is recovered from the bundle histogram by a
precomputed gather plus the reference's FixHistogram identity
(dataset.cpp:1410): hist[mfb] = leaf_total - sum(other bins).

Unbundled features use the same table machinery with identity values
(offset 0, skip = num_bin), so every consumer (partition, traversal,
histogram gather) has ONE uniform code path.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..utils import log
from .binning import BIN_CATEGORICAL

MAX_BUNDLE_BINS = 256          # default: keeps bundle codes uint8
MAX_SEARCH_GROUP = 100         # reference dataset.cpp:105 max_search_group
CONFLICT_FRACTION = 1.0 / 10000  # reference single_val_max_conflict_cnt


def find_bundles(nonzero_rows: List[np.ndarray], num_bins: Sequence[int],
                 bundle_ok: Sequence[bool], sample_cnt: int,
                 max_bundle_bins: int = MAX_BUNDLE_BINS,
                 max_conflict_rate: float = CONFLICT_FRACTION
                 ) -> List[List[int]]:
    """Greedy conflict-bounded grouping of features into bundles.

    nonzero_rows[f]: sorted sample-row indices where feature f is NOT at
    its most-frequent bin. bundle_ok[f]: feature is eligible (numerical,
    default==mfb). Returns a list of groups (lists of feature indices)
    covering every feature exactly once.

    Mirrors reference FindGroups (dataset.cpp:96): features are visited
    in descending non-default count, a feature joins the first existing
    group whose accumulated conflict count stays within
    sample_cnt * max_conflict_rate, else opens a new group. Both budgets
    are config knobs (efb_max_bundle_bins / efb_max_conflict_rate):
    denser bundling — wider groups, uint16 codes past 256 bins — is the
    lever the row-wise multival histogram layout wants, since its
    per-row code list shrinks with the group count.
    """
    f_total = len(nonzero_rows)
    max_conflict = int(sample_cnt * max_conflict_rate)
    order = sorted(range(f_total), key=lambda f: -len(nonzero_rows[f]))

    group_members: List[List[int]] = []
    group_marks: List[np.ndarray] = []   # bool over sample rows
    group_bins: List[int] = []
    group_confl: List[int] = []
    # probe screen: a fixed random row subset lets ONE matvec estimate
    # every group's conflict with a candidate feature, so the exact
    # check only visits the most promising MAX_SEARCH_GROUP groups.
    # The reference caps its search by sampling groups at RANDOM
    # (dataset.cpp:132-143) — at thousands of columns that misses the
    # compatible group most of the time; the probe finds it while the
    # conflict budget is still enforced EXACTLY below.
    probe_n = min(4096, sample_cnt)
    probe_rng = np.random.RandomState(3)
    probe_idx = np.sort(probe_rng.choice(sample_cnt, probe_n,
                                         replace=False)) \
        if probe_n < sample_cnt else np.arange(sample_cnt)
    probe_lut = np.full(sample_cnt, -1, np.int64)
    probe_lut[probe_idx] = np.arange(probe_n)
    probe_mat = np.zeros((f_total, probe_n), np.float32)  # row g = group g

    for f in order:
        if not bundle_ok[f]:
            group_members.append([f])
            group_marks.append(None)       # ineligible: never joined
            group_bins.append(num_bins[f])
            group_confl.append(0)
            continue
        rows = nonzero_rows[f]
        pf = probe_lut[rows]
        pf = pf[pf >= 0]
        pvec = np.zeros(probe_n, np.float32)
        pvec[pf] = 1.0
        placed = False
        g_count = len(group_members)
        gids = []
        if g_count:
            est = probe_mat[:g_count] @ pvec              # [G]
            # ineligible / bin-budget-full groups can never accept the
            # feature: push them past the end so they neither appear in
            # the candidate order nor consume exact-check budget
            blocked = np.fromiter(
                (group_marks[g] is None
                 or group_bins[g] + num_bins[f] - 1 > max_bundle_bins
                 for g in range(g_count)), dtype=bool, count=g_count)
            est[blocked] = np.inf
            gids = np.argsort(est, kind="stable")[:MAX_SEARCH_GROUP]
            gids = gids[np.isfinite(est[gids])]
        for gid in gids:
            cnt = int(np.count_nonzero(group_marks[gid][rows]))
            if group_confl[gid] + cnt <= max_conflict:
                group_members[gid].append(f)
                group_marks[gid][rows] = True
                group_bins[gid] += num_bins[f] - 1
                group_confl[gid] += cnt
                np.maximum(probe_mat[gid], pvec, out=probe_mat[gid])
                placed = True
                break
        if not placed:
            mark = np.zeros(sample_cnt, dtype=bool)
            mark[rows] = True
            group_members.append(list([f]))
            group_marks.append(mark)
            group_bins.append(num_bins[f])
            group_confl.append(0)
            probe_mat[len(group_members) - 1] = pvec
    return group_members


class BundleTables:
    """Per-feature bundle lookup tables (host numpy + lazy device copies).

    With no bundling these are identity tables: group_of = arange(F),
    offset 0, nslots = num_bin, skip = num_bin (decode is then the
    identity and every code is in-band).
    """

    def __init__(self, groups: List[List[int]], num_bins: Sequence[int],
                 mfb: Sequence[int]) -> None:
        f_total = len(num_bins)
        self.groups = groups
        self.num_groups = len(groups)
        self.group_of = np.zeros(f_total, dtype=np.int32)
        self.offset_of = np.zeros(f_total, dtype=np.int32)
        self.nslots_of = np.zeros(f_total, dtype=np.int32)
        self.skip_of = np.zeros(f_total, dtype=np.int32)
        self.bundled = np.zeros(f_total, dtype=bool)
        self.group_num_bins = np.zeros(self.num_groups, dtype=np.int32)
        for g, members in enumerate(groups):
            if len(members) == 1:
                f = members[0]
                self.group_of[f] = g
                self.offset_of[f] = 0
                self.nslots_of[f] = num_bins[f]
                self.skip_of[f] = num_bins[f]       # "skip nothing"
                self.group_num_bins[g] = num_bins[f]
            else:
                off = 1                              # code 0 = all-default
                for f in members:
                    self.group_of[f] = g
                    self.offset_of[f] = off
                    self.nslots_of[f] = num_bins[f] - 1
                    self.skip_of[f] = mfb[f]
                    self.bundled[f] = True
                    off += num_bins[f] - 1
                self.group_num_bins[g] = off
        self._device = None
        self._hist_tables = None

    @property
    def is_trivial(self) -> bool:
        return not self.bundled.any()

    @classmethod
    def identity(cls, num_bins: Sequence[int]) -> "BundleTables":
        return cls([[f] for f in range(len(num_bins))], num_bins,
                   [0] * len(num_bins))

    # ------------------------------------------------------------------
    def device(self):
        """(group_of, offset_of, nslots_of, skip_of) as device arrays."""
        if self._device is None:
            import jax.numpy as jnp
            self._device = (jnp.asarray(self.group_of),
                            jnp.asarray(self.offset_of),
                            jnp.asarray(self.nslots_of),
                            jnp.asarray(self.skip_of))
        return self._device

    def hist_tables(self, num_bins: Sequence[int], max_feature_bins: int):
        """Precomputed gather tables mapping the flattened bundle
        histogram [G * Bg] to per-feature histograms [F, Bmax]:
        (gather_idx, valid, mfb_onehot) device arrays."""
        if self._hist_tables is None:
            import jax.numpy as jnp
            f_total = len(self.group_of)
            bg = int(self.group_num_bins.max()) if self.num_groups else 1
            idx = np.zeros((f_total, max_feature_bins), dtype=np.int32)
            valid = np.zeros((f_total, max_feature_bins), dtype=np.float32)
            mfb_oh = np.zeros((f_total, max_feature_bins), dtype=np.float32)
            for f in range(f_total):
                g, off = self.group_of[f], self.offset_of[f]
                skip = self.skip_of[f]
                for b in range(num_bins[f]):
                    if self.bundled[f] and b == skip:
                        mfb_oh[f, b] = 1.0   # reconstructed by FixHistogram
                        continue
                    slot = b - (1 if b > skip else 0)
                    idx[f, b] = g * bg + off + slot
                    valid[f, b] = 1.0
            self._hist_tables = (jnp.asarray(idx), jnp.asarray(valid),
                                 jnp.asarray(mfb_oh), bg)
        return self._hist_tables


# ---------------------------------------------------------------------------
# Device-side helpers (uniform for bundled and unbundled features)
# ---------------------------------------------------------------------------

def decode_bins(codes, feature, tables_dev):
    """Per-row feature-local bin from bundle codes.

    codes: [R] int32 — the rows' values of the feature's GROUP column
    (caller gathers bins[:, group_of[feature]]). Returns [R] int32 bins
    in the feature's own bin space; out-of-band codes (other members
    non-default, or all-default) map to the feature's most-frequent bin.
    """
    import jax.numpy as jnp
    _, offset_of, nslots_of, skip_of = tables_dev
    off = offset_of[feature]
    nsl = nslots_of[feature]
    skip = skip_of[feature]
    rel = codes - off
    inband = (rel >= 0) & (rel < nsl)
    decoded = rel + (rel >= skip)
    return jnp.where(inband, decoded, skip).astype(jnp.int32)


def per_feature_hist(group_hist, hist_tables, sum_g, sum_h):
    """Bundle histogram [G, Bg, 2] → per-feature histogram [F, Bmax, 2].

    Reconstructs each bundled feature's most-frequent-bin entry as
    leaf_total - sum(other bins) — the reference's FixHistogram
    (dataset.cpp:1410) using the leaf sums the split scan already has.
    """
    import jax.numpy as jnp
    gather_idx, valid, mfb_oh, bg = hist_tables
    flat = group_hist.reshape(-1, 2)
    # astype keeps quantized int32 histograms in exact integer space
    # (no-op for the f32 path: valid/mfb_oh are stored f32)
    fh = flat[gather_idx] * valid[..., None].astype(flat.dtype)
    total = jnp.stack([sum_g, sum_h]).astype(fh.dtype)  # [2]
    rest = fh.sum(axis=1)                              # [F, 2]
    fill = total[None, :] - rest                       # [F, 2]
    return fh + mfb_oh[..., None].astype(fh.dtype) * fill[:, None, :]


def bundle_eligible(m) -> bool:
    """Numerical features whose default (zero) bin is the most-frequent
    bin survive the encoding losslessly; everything else stays single."""
    return (m.bin_type != BIN_CATEGORICAL
            and m.default_bin == m.most_freq_bin and m.num_bin >= 2)


def build_bundles(nonzero_rows: List[np.ndarray], mappers,
                  sample_cnt: int, enable: bool,
                  bundle_ok: Optional[Sequence[bool]] = None,
                  max_bundle_bins: int = MAX_BUNDLE_BINS,
                  max_conflict_rate: float = CONFLICT_FRACTION
                  ) -> BundleTables:
    """Decide bundling from per-feature sampled non-default row sets.

    nonzero_rows[f]: sample-row indices where feature f's bin != its
    most-frequent bin (empty for ineligible features). Returns identity
    tables when bundling is disabled or not profitable. Codes are uint8
    while every group fits 256 bins and widen to uint16 past that
    (io/dataset.py _apply_mappers picks the dtype off group_num_bins).
    """
    num_bins = [m.num_bin for m in mappers]
    f_total = len(mappers)
    if not enable or f_total <= 1:
        return BundleTables.identity(num_bins)
    if bundle_ok is None:
        bundle_ok = [bundle_eligible(m) for m in mappers]
    groups = find_bundles(nonzero_rows, num_bins, bundle_ok, sample_cnt,
                          max_bundle_bins=max_bundle_bins,
                          max_conflict_rate=max_conflict_rate)
    if len(groups) >= f_total:
        return BundleTables.identity(num_bins)
    mfb = [m.most_freq_bin for m in mappers]
    tables = BundleTables(groups, num_bins, mfb)
    n_multi = sum(1 for g in groups if len(g) > 1)
    log.info("EFB: bundled %d features into %d groups (%d multi-feature)",
             f_total, len(groups), n_multi)
    return tables
