"""Distributed dataset construction: sharded bin finding.

TPU re-design of the reference's distributed loading protocol
(reference: src/io/dataset_loader.cpp:917-990
ConstructBinMappersFromTextData — when num_machines > 1, features are
partitioned across machines by sample workload, each machine finds bin
boundaries for its owned features from its LOCAL row sample, and the
serialized BinMappers ride a Network::Allgather at :984 so every
machine ends with the identical full mapper set).

Here the machine list is a JAX mesh axis: each shard (host) samples its
own rows, bins its owned features host-side (binning is irreducibly
scalar host work, exactly as in the reference), and the serialized
mapper bytes ride `jax.lax.all_gather` over the mesh — ICI/DCN instead
of sockets. The single-controller test harness drives every rank in one
process over a virtual CPU mesh; a true multi-host deployment calls
`construct_bin_mappers_distributed` once per host with its own shard.
"""
from __future__ import annotations

import json
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..utils import log
from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, K_ZERO_THRESHOLD,
                      BinMapper)


def partition_features(num_features: int, world: int,
                       workload: Optional[Sequence[int]] = None
                       ) -> List[List[int]]:
    """Greedy workload-balanced assignment of features to ranks
    (reference dataset_loader.cpp:928-950 assigns contiguous blocks
    sized by num_machines; we balance by per-feature sample workload
    with a largest-first greedy, which the reference's feature-parallel
    learner also uses)."""
    if workload is None:
        workload = [1] * num_features
    order = sorted(range(num_features), key=lambda f: -workload[f])
    loads = [0] * world
    owned: List[List[int]] = [[] for _ in range(world)]
    for f in order:
        r = int(np.argmin(loads))
        owned[r].append(f)
        loads[r] += workload[f]
    for lst in owned:
        lst.sort()
    return owned


def find_bins_for_features(sample: np.ndarray, features: Sequence[int],
                           config: Config, total_sample_cnt: int,
                           cat_set=frozenset(), pre_filter: bool = False
                           ) -> List[Tuple[int, BinMapper]]:
    """Host-side bin finding for a feature subset over a local sample
    (reference BinMapper::FindBin over the machine's own sample rows).

    pre_filter defaults off because on a true multi-host shard it would
    need global stats; the single-controller driver passes the config
    value through (its "local" sample IS the global sample).

    ``sample`` may be a scipy CSC matrix: a column's stored values are
    exactly the dense column minus structural zeros, which the
    |col| > kZeroThreshold filter below would drop anyway — boundaries
    are bit-identical to the dense path (asserted by
    tests/test_distributed_binning.py)."""
    is_sparse = hasattr(sample, "getformat")
    if is_sparse and sample.getformat() != "csc":
        sample = sample.tocsc()
    out = []
    for f in features:
        if is_sparse:
            col = np.asarray(
                sample.data[sample.indptr[f]:sample.indptr[f + 1]],
                dtype=np.float64)
        else:
            col = np.asarray(sample[:, f], dtype=np.float64)
        nonzero = col[(np.abs(col) > K_ZERO_THRESHOLD) | np.isnan(col)]
        m = BinMapper()
        mb = (config.max_bin_by_feature[f]
              if config.max_bin_by_feature and f < len(config.max_bin_by_feature)
              else config.max_bin)
        m.find_bin(nonzero, total_sample_cnt, mb,
                   min_data_in_bin=config.min_data_in_bin,
                   min_split_data=config.min_data_in_leaf,
                   pre_filter=pre_filter,
                   bin_type=BIN_CATEGORICAL if f in cat_set else BIN_NUMERICAL,
                   use_missing=config.use_missing,
                   zero_as_missing=config.zero_as_missing)
        out.append((f, m))
    return out


def serialize_mappers(pairs: List[Tuple[int, BinMapper]],
                      pad_to: Optional[int] = None) -> np.ndarray:
    """(feature, mapper) list -> fixed-size uint8 buffer (the wire
    format of the reference's BinMapper::CopyTo, bin.h, except JSON
    instead of raw structs — the payload is boundaries, not data)."""
    payload = json.dumps([(f, m.to_dict()) for f, m in pairs]).encode()
    buf = np.frombuffer(payload, dtype=np.uint8)
    header = np.frombuffer(np.int64(len(buf)).tobytes(), dtype=np.uint8)
    out = np.concatenate([header, buf])
    if pad_to is not None:
        if len(out) > pad_to:
            raise ValueError(f"serialized mappers ({len(out)}B) exceed "
                             f"buffer ({pad_to}B)")
        out = np.pad(out, (0, pad_to - len(out)))
    return out


def deserialize_mappers(buf: np.ndarray) -> List[Tuple[int, BinMapper]]:
    n = int(np.frombuffer(bytes(buf[:8]), dtype=np.int64)[0])
    payload = bytes(buf[8:8 + n])
    return [(int(f), BinMapper.from_dict(d))
            for f, d in json.loads(payload.decode())]


def allgather_bytes(shard_bufs: np.ndarray, mesh=None) -> np.ndarray:
    """All-gather fixed-size per-rank byte buffers over the mesh's
    "data" axis — the TPU stand-in for Network::Allgather
    (dataset_loader.cpp:984). shard_bufs: [world, L] uint8 with row r
    owned by rank r; returns the replicated [world, L]."""
    import jax
    import jax.numpy as jnp
    from ..utils.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        from ..treelearner.parallel import build_mesh
        mesh = build_mesh(Config())
    world = shard_bufs.shape[0]
    dev = jax.device_put(
        jnp.asarray(shard_bufs),
        NamedSharding(mesh, P("data", None)))

    def _gather(b):
        return jax.lax.all_gather(b[0], "data")

    # explicit shard_map call form (not a lambda decorator) so the
    # static call graph sees _gather as the mapped body binding "data"
    # tpulint: jit-ok(one-shot collective gather; not a training entry)
    gather = jax.jit(shard_map(_gather, mesh=mesh,
                               in_specs=P("data", None), out_specs=P(),
                               check_vma=False))

    from ..network import collective_span
    with collective_span("allgather", int(dev.nbytes), axis="data"):
        return np.asarray(gather(dev))


def construct_bin_mappers_distributed(
        local_sample: np.ndarray, rank: int, world: int, config: Config,
        cat_set=frozenset(), total_sample_cnt: Optional[int] = None,
        pre_filter: bool = False) -> List[Tuple[int, BinMapper]]:
    """One rank's local half of the distributed bin-finding protocol:
    bins this rank's OWNED feature subset from its local sample and
    returns the (feature, mapper) pairs. The collective half is
    `serialize_mappers` -> `allgather_bytes` -> `merge_gathered_mappers`
    (see the module docstring for the full flow; reference
    ConstructBinMappersFromTextData keeps the same local/Allgather
    split, dataset_loader.cpp:917-990).
    """
    f_total = local_sample.shape[1]
    owned = partition_features(f_total, world)[rank]
    total = total_sample_cnt or int(local_sample.shape[0])
    return find_bins_for_features(local_sample, owned, config, total,
                                  cat_set, pre_filter=pre_filter)


def merge_gathered_mappers(gathered: np.ndarray,
                           f_total: int) -> List[BinMapper]:
    """Replicated [world, L] buffers -> full ordered mapper list."""
    mappers: List[Optional[BinMapper]] = [None] * f_total
    for r in range(gathered.shape[0]):
        for f, m in deserialize_mappers(gathered[r]):
            mappers[f] = m
    missing = [f for f, m in enumerate(mappers) if m is None]
    if missing:
        log.fatal("Distributed bin finding left features without "
                  "mappers: %s", missing)
    return mappers


def distributed_find_bin_mappers(sample: np.ndarray, config: Config,
                                 cat_set=frozenset()) -> List[BinMapper]:
    """The full num_machines>1 construction protocol, single-controller
    driven (reference ConstructBinMappersFromTextData,
    dataset_loader.cpp:917-990):

    1. features are ownership-partitioned across ranks,
    2. each rank bins its OWNED feature subset,
    3. the serialized mappers ride an all-gather over the device mesh
       (Network::Allgather at :984 -> jax.lax.all_gather over ICI),
    4. every rank merges the identical full mapper set.

    Unlike the reference — where each machine physically holds only a
    round-robin row shard, so its features are binned from 1/world of
    the sample (dataset_loader.cpp:167) — the single-controller process
    has the ENTIRE sample in memory, so each rank bins its owned
    features over the full sample. Bin boundaries are therefore
    bit-identical to single-machine construction (num_machines is a
    work-partitioning choice, not a data-quality tradeoff); only a true
    multi-host deployment, where ranks call
    `construct_bin_mappers_distributed` on genuinely local shards, sees
    the reference's local-sample semantics.
    """
    import jax

    world = int(config.num_machines)
    n, f_total = sample.shape
    if hasattr(sample, "getformat"):
        # sparse samples ride the same protocol: column slices come
        # straight from the CSC structure, never densified
        full = sample.tocsc()
    else:
        full = np.asarray(sample, dtype=np.float64)
    pairs = [construct_bin_mappers_distributed(
        full, r, world, config, cat_set, total_sample_cnt=n,
        pre_filter=config.feature_pre_filter)
        for r in range(world)]
    bufs = [serialize_mappers(p) for p in pairs]
    pad = -(-max(len(b) for b in bufs) // 128) * 128
    stacked = np.stack([np.pad(b, (0, pad - len(b))) for b in bufs])
    ndev = len(jax.devices())
    if ndev >= world:
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:world]), ("data",))
        gathered = allgather_bytes(stacked, mesh)
    else:
        # fewer devices than machines (e.g. single-chip run of a
        # num_machines config): the collective degenerates to the
        # already-assembled buffer — protocol output is identical
        log.info("num_machines=%d > %d devices: bin-mapper allgather "
                 "runs host-side", world, ndev)
        gathered = stacked
    return merge_gathered_mappers(gathered, f_total)
