"""Training entry points: train() and cv().

API-compatible re-implementation of the reference engine
(reference: python-package/lightgbm/engine.py — train() at :18 with the
callback/early-stopping protocol, cv() at :394 with stratified folds and
CVBooster at :280).
"""
from __future__ import annotations

import collections
import copy
import os
from typing import Any, Dict, List, Optional

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset, LightGBMError
from .config import Config, _ALIASES
from .utils import log


def _resolve_num_boost_round(params: Dict[str, Any], default: int) -> int:
    for alias in ("num_iterations", "num_iteration", "n_iter", "num_tree",
                  "num_trees", "num_round", "num_rounds", "num_boost_round",
                  "n_estimators"):
        if alias in params:
            return int(params.pop(alias))
    return default


def _resolve_early_stopping(params: Dict[str, Any],
                            explicit: Optional[int]) -> Optional[int]:
    for alias in ("early_stopping_round", "early_stopping_rounds",
                  "early_stopping", "n_iter_no_change"):
        if alias in params:
            return int(params.pop(alias))
    return explicit


def _ensure_jit_cache() -> None:
    """Persistent XLA compile cache shared by every entry point (train,
    cv, bench): fold 2..k of a cv() and repeat runs of the same shapes
    skip compilation entirely. Respects a user-configured cache dir."""
    import jax
    try:
        if jax.config.jax_compilation_cache_dir:
            return
        cache = os.environ.get(
            "LGBM_TPU_JIT_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "lightgbm_tpu", "xla"))
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def _telemetry_end_iteration(telemetry, booster, iteration: int,
                             evals) -> None:
    """Snapshot one iteration into the telemetry session: sync the
    device stream first (metrics mode only — the disabled path never
    pays this) so the wall time is honest, then attach model stats and
    eval metrics."""
    import jax
    from . import obs
    gbdt = booster._gbdt
    extra: Dict[str, Any] = {}
    if not telemetry.record_consumers_active():
        # every record consumer is gone (the sink died on an I/O error,
        # nothing else is on): don't pay the stream sync + device stat
        # fetches just to format a payload that gets dropped — the
        # registry still keeps its lifecycle and counts the drop
        telemetry.end_iteration(iteration)
        return
    try:
        with obs.span("telemetry stream sync", phase="sync"):
            # tpulint: sync-ok(telemetry-only stream sync for honest wall time)
            jax.block_until_ready(gbdt.device_score_state())
    except Exception:
        pass
    try:
        with obs.span("telemetry stats", phase="telemetry"):
            extra.update(gbdt.telemetry_stats())
    except Exception as exc:
        log.debug("telemetry_stats failed: %s", exc)
    if evals:
        extra["metrics"] = {f"{ds}/{m}": float(v)
                            for ds, m, v, _ in evals}
    telemetry.end_iteration(iteration, extra=extra)


def _checkpoint_capture(booster: Booster, cbs) -> tuple:
    """(state, model_text) snapshot of everything resume needs: the
    boosting loop state (gbdt.checkpoint_state), each checkpoint-aware
    callback's state (keyed by its checkpoint_key), and the running
    best_iteration. The model itself travels as the reference text
    format, so a checkpoint is also a valid saved model."""
    gbdt = booster._gbdt
    state: Dict[str, Any] = {
        "gbdt": gbdt.checkpoint_state(),
        "best_iteration": int(booster.best_iteration),
        "callbacks": {},
    }
    for cb in cbs:
        key = getattr(cb, "checkpoint_key", None)
        if key and hasattr(cb, "checkpoint_state"):
            state["callbacks"][key] = cb.checkpoint_state()
    return state, gbdt.save_model_to_string()


def _checkpoint_restore(booster: Booster, cbs, state: Dict[str, Any],
                        model_text: str) -> None:
    booster._gbdt.restore_checkpoint_state(state["gbdt"], model_text)
    booster.best_iteration = int(state.get("best_iteration", -1))
    cb_states = state.get("callbacks", {})
    for cb in cbs:
        key = getattr(cb, "checkpoint_key", None)
        if key and key in cb_states \
                and hasattr(cb, "restore_checkpoint_state"):
            cb.restore_checkpoint_state(cb_states[key])


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100, valid_sets=None, valid_names=None,
          fobj=None, feval=None, init_model=None, feature_name: str = "auto",
          categorical_feature: str = "auto",
          early_stopping_rounds: Optional[int] = None, evals_result=None,
          verbose_eval=True, learning_rates=None,
          keep_training_booster: bool = False, callbacks=None,
          checkpoint_dir: Optional[str] = None) -> Booster:
    """reference engine.py:18.

    `checkpoint_dir` (also settable as the `checkpoint_dir` param)
    enables preemption-safe training: atomic checkpoints every
    `checkpoint_interval` iterations, and auto-resume from the latest
    valid checkpoint when one exists (docs/ROBUSTNESS.md)."""
    params = copy.deepcopy(params) if params else {}
    _ensure_jit_cache()
    from .compile import preload_store_async
    preload_store_async()
    # multi-host process wiring BEFORE any dataset construction, so the
    # distributed bin-mapper allgather and the training mesh see the
    # global device set (reference Application::InitTrain calls
    # Network::Init first, application.cpp:164-175). Alias resolution
    # goes through Config so "workers"/"nodes"/"num_machine" work here
    # exactly as everywhere else.
    net_cfg = Config.from_params({
        k: v for k, v in params.items()
        if Config.resolve_alias(k) in ("num_machines", "machines",
                                       "time_out")})
    if net_cfg.num_machines > 1:
        # with an empty machine list this is env-driven
        # (JAX_COORDINATOR_ADDRESS) or a single-controller no-op —
        # ensure_distributed sorts the cases out
        from .network import ensure_distributed
        ensure_distributed(net_cfg.machines, net_cfg.num_machines,
                           time_out=net_cfg.time_out)
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")
    from .utils.timer import global_timer
    _timetag = [v for k, v in params.items()
                if Config.resolve_alias(k) == "timetag"]
    if _timetag:
        # explicit per-train toggle wins over env/verbosity
        from .config import _parse_bool
        global_timer.set_enabled(_parse_bool(_timetag[0]))
    elif not os.environ.get("LGBM_TPU_TIMETAG"):
        # reference -DUSE_TIMETAG phase table (common.h:1054): opt-in
        # via the env knob or verbose>=2 (assign BOTH ways so a quiet
        # train after a verbose one stops paying the annotations)
        global_timer.enabled =             int(params.get("verbose", params.get("verbosity", 1)) or 0) >= 2

    early_stopping_rounds = _resolve_early_stopping(params, early_stopping_rounds)
    first_metric_only = params.get("first_metric_only", False)

    if fobj is not None:
        params["objective"] = "none"
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    predictor_model = None
    if init_model is not None:
        if isinstance(init_model, str):
            predictor_model = Booster(model_file=init_model)
        elif isinstance(init_model, Booster):
            predictor_model = init_model

    # continued training: initialize train/valid scores by predicting the
    # old model over the raw data (reference basic.py
    # _set_init_score_by_predictor:1019)
    if predictor_model is not None and train_set.init_score is None:
        raw = train_set.data
        if raw is None:
            raise LightGBMError("Cannot continue training when the raw data "
                                "was freed; pass free_raw_data=False")
        init_score = predictor_model.predict(raw, raw_score=True)
        train_set.init_score = init_score.T.reshape(-1) if init_score.ndim == 2 \
            else init_score

    with global_timer.scope("dataset construction + learner build"):
        booster = Booster(params=params, train_set=train_set)
    from .compile import background_warmup, warmup_wanted
    if warmup_wanted(booster._gbdt.config, train_set.num_data()):
        # compile the registered entry specs on a thread pool while the
        # caller is still wiring callbacks/valid sets; the first training
        # iteration then dispatches straight into warm executables
        background_warmup()
    if predictor_model is not None:
        k = predictor_model._gbdt.num_tree_per_iteration
        from .basic import copy_tree
        predictor_model._gbdt._materialize_models()
        booster._gbdt.models = [copy_tree(t) for t in predictor_model._gbdt.models] \
            + booster._gbdt.models
        booster._gbdt.num_init_iteration = len(predictor_model._gbdt.models) // k
        booster._gbdt.iter = 0

    valid_contain_train = False
    train_data_name = "training"
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if valid_names is not None and isinstance(valid_names, str):
            valid_names = [valid_names]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                valid_contain_train = True
                if valid_names is not None:
                    train_data_name = valid_names[i]
                continue
            if predictor_model is not None and vs.init_score is None \
                    and vs.data is not None:
                isc = predictor_model.predict(vs.data, raw_score=True)
                vs.init_score = isc.T.reshape(-1) if isc.ndim == 2 else isc
            name = valid_names[i] if valid_names is not None else f"valid_{i}"
            booster.add_valid(vs, name)

    cbs = set(callbacks) if callbacks else set()
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval is not False:
        cbs.add(callback_mod.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(early_stopping_rounds,
                                            first_metric_only,
                                            verbose=bool(verbose_eval)))
    if learning_rates is not None:
        cbs.add(callback_mod.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        cbs.add(callback_mod.record_evaluation(evals_result))

    callbacks_before = {cb for cb in cbs if getattr(cb, "before_iteration", False)}
    callbacks_after = cbs - callbacks_before
    callbacks_before = sorted(callbacks_before, key=lambda cb: getattr(cb, "order", 0))
    callbacks_after = sorted(callbacks_after, key=lambda cb: getattr(cb, "order", 0))

    # preemption safety (docs/ROBUSTNESS.md): periodic atomic
    # checkpoints + auto-resume. Wired AFTER callback assembly so
    # checkpoint-aware callbacks (early stopping, record_evaluation)
    # can hand their state back on resume.
    from .robust.checkpoint import CheckpointManager
    from .robust.faultinject import check_fault
    cfg = booster._gbdt.config
    ckpt_dir = checkpoint_dir if checkpoint_dir else cfg.checkpoint_dir
    ckpt_mgr = None
    start_iteration = 0
    if ckpt_dir:
        from .compile import signature as S
        digest = S._digest(S.config_signature(cfg))
        ckpt_mgr = CheckpointManager(
            ckpt_dir, interval=cfg.checkpoint_interval,
            keep=cfg.checkpoint_keep, params_digest=digest)
        if init_model is None:
            resumed = ckpt_mgr.load_latest()
            if resumed is not None:
                it, ck_state, ck_model = resumed
                _checkpoint_restore(booster, cbs, ck_state, ck_model)
                start_iteration = it + 1
                log.info("Resuming from checkpoint %s: %d iterations "
                         "already trained", ckpt_mgr.path_for(it),
                         start_iteration)
        else:
            # reference init_model semantics win: an explicit warm
            # start means the caller is managing continuation itself
            log.warning("checkpoint_dir=%s ignored for resume because "
                        "init_model was given (checkpoints will still "
                        "be written)", ckpt_dir)

    from . import obs
    telemetry = obs.TelemetrySession.from_config(booster._gbdt.config)
    if telemetry is not None:
        telemetry.start()
        telemetry.registry.set_gauge("train.total_iterations",
                                     float(num_boost_round))
    # dispatch-ahead pipelining (default; LGBM_TPU_PIPELINE=0 restores
    # the fully synchronous loop): iteration t's eval-scalar readback
    # and after-iteration callbacks run only after iteration t+1's
    # device work has been dispatched, so the host never idles waiting
    # for metrics. Early stopping therefore observes iteration t one
    # step late — it can never stop EARLIER than the synchronous loop,
    # trains at most one extra tree, and records the same
    # best_iteration (which the saved model is truncated to, so saved
    # output is identical). Full telemetry mode stays synchronous: its
    # per-iteration stream sync serializes the loop anyway, and every
    # JSONL record must carry its own iteration's metrics. LIGHTWEIGHT
    # sessions (obs_port / flight_dir only, no metrics_file) ride the
    # pipelined loop: their per-iteration bookkeeping is host-side
    # registry arithmetic plus at most the one fleet allgather, never a
    # stream sync or a device stat fetch.
    # feval also forces the synchronous loop: a custom eval reads the
    # LIVE score arrays at call time, so a deferred call would see the
    # next iteration's scores
    full_telemetry = telemetry is not None and not telemetry.lightweight
    pipeline = (not full_telemetry and feval is None
                and os.environ.get("LGBM_TPU_PIPELINE", "1") != "0")
    evaluation_result_list: Optional[list] = None
    pending = None    # (iteration, unresolved eval handle)

    def _resolve_evals(handle) -> list:
        evals: list = []
        with obs.span("metric evaluation (resolve)", phase="eval"):
            res = booster._gbdt.finish_eval_at_iter(handle) \
                if handle is not None else None
            if valid_contain_train:
                evals.extend((train_data_name, m, v, b)
                             for _, m, v, b
                             in booster.eval_train(feval, res=res))
            if booster.name_valid_sets:
                evals.extend(booster.eval_valid(feval, res=res))
        return evals

    def _after_callbacks(it: int, evals) -> None:
        with watch_phase("host-callback:after"):
            for cb in callbacks_after:
                cb(callback_mod.CallbackEnv(model=booster, params=params,
                                            iteration=it, begin_iteration=0,
                                            end_iteration=num_boost_round,
                                            evaluation_result_list=evals))

    # self-healing (docs/ROBUSTNESS.md): a hang watchdog arms a deadman
    # timer over the loop; numeric-sentinel verdicts ride the trailing
    # fetches; the recovery policy below quarantines bad trees, rolls
    # back to the last checkpoint, and steps down the degraded-mode
    # ladder instead of hanging forever or training garbage
    from .robust.sentinel import apply_degraded_rung
    from .robust.watchdog import (HangTimeout, Watchdog, activate_watchdog,
                                  deactivate_watchdog, watch_phase)
    wd = None
    if cfg.hang_timeout > 0:
        wd = Watchdog(cfg.hang_timeout,
                      trace_path=(cfg.trace_file + ".watchdog.json"
                                  if cfg.trace_file
                                  else "watchdog_trace.json"),
                      # the first iterations block on whole-program
                      # compiles; a short timeout must not call that a
                      # hang (and there is no checkpoint to resume from
                      # yet)
                      warmup_grace_s=max(60.0, 4 * cfg.hang_timeout))
        activate_watchdog(wd)
        wd.start()
    resume_attempts = 0
    degraded_rung = 0

    def _restore_latest() -> bool:
        """Roll the LIVE booster back to the newest checkpoint; updates
        start_iteration for loop re-entry. In-flight eval handles are
        dropped — they belong to the abandoned timeline."""
        nonlocal start_iteration, pending
        if ckpt_mgr is None:
            return False
        resumed = ckpt_mgr.load_latest()
        if resumed is None:
            return False
        pending = None
        it, ck_state, ck_model = resumed
        _checkpoint_restore(booster, cbs, ck_state, ck_model)
        start_iteration = it + 1
        return True
    try:
      while True:
        restart = False
        try:
            for i in range(start_iteration, num_boost_round):
                if wd is not None:
                    wd.beat(i)
                    wd.check()
                spec = check_fault("train.iteration", index=i)
                if spec is not None and spec.mode in ("nan", "overflow"):
                    # drill: the next gradient plane is poisoned; the
                    # numeric sentinels must catch the divergence
                    booster._gbdt._poison_next = spec.mode
                if telemetry is not None:
                    telemetry.begin_iteration(i)
                with obs.span("before-iteration callbacks",
                              phase="callbacks"), \
                        watch_phase("host-callback:before"):
                    for cb in callbacks_before:
                        cb(callback_mod.CallbackEnv(
                            model=booster, params=params, iteration=i,
                            begin_iteration=0,
                            end_iteration=num_boost_round,
                            evaluation_result_list=None))
                with obs.span("boosting iteration (device dispatch)",
                              phase="update"), \
                        watch_phase("dispatch:update"):
                    finished = booster.update(fobj=fobj)

                with obs.span("metric evaluation", phase="eval"):
                    eval_handle = (
                        booster._gbdt.begin_eval_at_iter()
                        if valid_contain_train or booster.name_valid_sets
                        else None)
                if full_telemetry:
                    evaluation_result_list = _resolve_evals(eval_handle)
                    eval_handle = None
                    _telemetry_end_iteration(telemetry, booster, i,
                                             evaluation_result_list)
                elif telemetry is not None:
                    # lightweight: registry wall-clock + fleet merge +
                    # SLO check only — no stream sync, no device fetch;
                    # the window ends at dispatch, trailing resolve time
                    # is attributed to the next iteration
                    telemetry.end_iteration(i)
                drained_it = i
                try:
                    if full_telemetry:
                        _after_callbacks(i, evaluation_result_list)
                    else:
                        # trailing resolve: the PREVIOUS iteration's eval
                        # readback and callbacks run while this iteration's
                        # device work is already in flight
                        if pending is not None:
                            pit, ph = pending
                            pending = None
                            drained_it = pit
                            evaluation_result_list = _resolve_evals(ph)
                            _after_callbacks(pit, evaluation_result_list)
                        pending = (i, eval_handle)
                        if not pipeline or finished:
                            pit, ph = pending
                            pending = None
                            drained_it = pit
                            evaluation_result_list = _resolve_evals(ph)
                            _after_callbacks(pit, evaluation_result_list)
                except callback_mod.EarlyStopException as e:
                    booster.best_iteration = e.best_iteration + 1
                    evaluation_result_list = e.best_score
                    if drained_it < i:
                        reg = obs.active()
                        if reg is not None:
                            # the stop decision arrived one dispatch late:
                            # iteration i was already trained (and is
                            # truncated away through best_iteration)
                            reg.inc("pipeline.delayed_stop_iters")
                    break
                sent = booster._gbdt._sentinel
                if sent is not None \
                        and booster._gbdt.process_sentinel_trips():
                    # repeated numeric trips: quarantine was not enough,
                    # so roll back to the last checkpoint and give up
                    # one optimization rung per recovery epoch
                    rung = apply_degraded_rung(booster._gbdt,
                                               degraded_rung)
                    if rung is not None:
                        degraded_rung += 1
                    if _restore_latest():
                        reg = obs.active()
                        if reg is not None:
                            reg.inc("health.rollbacks")
                        sent.drop_pending()
                        sent.reset_trips()
                        log.warning(
                            "sentinel: rolled back to iteration %d after "
                            "%d numeric-health trips", start_iteration,
                            sent.total_trips)
                        restart = True
                        break
                    # no checkpoint to return to: the offending trees
                    # are already quarantined, keep training degraded
                    sent.reset_trips()
                if finished:
                    break
                if ckpt_mgr is not None and ckpt_mgr.due(i):
                    # the pipelined loop drains first: callback state and
                    # eval records must cover iteration i before capture,
                    # exactly as the synchronous order would have them
                    if pending is not None:
                        try:
                            pit, ph = pending
                            pending = None
                            evaluation_result_list = _resolve_evals(ph)
                            _after_callbacks(pit, evaluation_result_list)
                        except callback_mod.EarlyStopException as e:
                            booster.best_iteration = e.best_iteration + 1
                            evaluation_result_list = e.best_score
                            break
                    with obs.span("checkpoint save", phase="checkpoint"):
                        ck_state, ck_model = _checkpoint_capture(booster, cbs)
                        ckpt_mgr.save(i, ck_state, ck_model)
            if restart:
                continue
            # post-loop drain: the final iteration's callbacks (including
            # the early-stopper's is-last announcement) when the loop ran
            # to its end with an iteration still in flight
            if pending is not None:
                try:
                    pit, ph = pending
                    pending = None
                    evaluation_result_list = _resolve_evals(ph)
                    _after_callbacks(pit, evaluation_result_list)
                except callback_mod.EarlyStopException as e:
                    booster.best_iteration = e.best_iteration + 1
                    evaluation_result_list = e.best_score
            break
        except HangTimeout:
            resume_attempts += 1
            if not cfg.auto_resume \
                    or resume_attempts > cfg.auto_resume_attempts \
                    or not _restore_latest():
                # no checkpoint (or attempts exhausted): surface the
                # watchdog's classified, actionable diagnosis
                raise
            if booster._gbdt._sentinel is not None:
                booster._gbdt._sentinel.drop_pending()
            if wd is not None:
                wd.clear()
            reg = obs.active()
            if reg is not None:
                reg.inc("watchdog.auto_resume")
            log.warning(
                "watchdog: auto-resuming from iteration %d after a "
                "detected hang (attempt %d/%d)", start_iteration,
                resume_attempts, cfg.auto_resume_attempts)
      # resolve any sentinel verdicts still in flight so a trip on the
      # final trees still quarantines them before the model is
      # finalized — before the finally below deactivates the flight
      # recorder, so a tail-end trip still dumps its evidence bundle
      if getattr(booster._gbdt, "_sentinel", None) is not None:
          booster._gbdt.sentinel_drain()
          booster._gbdt.process_sentinel_trips()
    finally:
        if wd is not None:
            deactivate_watchdog(wd)
            wd.stop()
        if telemetry is not None:
            telemetry.close()

    # fused path trains blind between periodic stop checks; drop any
    # trailing all-degenerate iterations it may have accumulated
    if getattr(booster._gbdt, "_fused", None) is not None:
        with global_timer.scope("degenerate-tail check (device sync)"):
            booster._gbdt._trim_degenerate_tail()
    if global_timer.enabled and global_timer.acc:
        from .utils import log as _log
        _log.info("%s", global_timer.report())
        global_timer.reset()   # per-train tables; also avoids the
        # atexit re-print of already-reported scopes

    for ds_name, m_name, val, _ in (evaluation_result_list or []):
        booster.best_score.setdefault(ds_name, collections.OrderedDict())
        booster.best_score[ds_name][m_name] = val
    if not keep_training_booster:
        booster.free_dataset()
    return booster


class CVBooster:
    """Ensemble of per-fold boosters (reference engine.py:280)."""

    def __init__(self) -> None:
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name: str):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: Dict,
                  seed: int, fpreproc=None, stratified: bool = True,
                  shuffle: bool = True, eval_train_metric: bool = False):
    full_data = full_data.construct()
    num_data = full_data.num_data()
    group = full_data.get_group()
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError("folds should be a generator or iterator of "
                                 "(train_idx, test_idx) tuples or scikit-learn splitter")
        if hasattr(folds, "split"):
            folds = folds.split(X=np.empty(num_data), y=full_data.get_label(),
                                groups=None)
    else:
        if group is not None:
            # group-aware folds: whole queries assigned to folds
            ng = len(group)
            rng = np.random.RandomState(seed)
            gidx = rng.permutation(ng) if shuffle else np.arange(ng)
            bounds = np.concatenate([[0], np.cumsum(group)]).astype(np.int64)
            fold_groups = np.array_split(gidx, nfold)
            folds = []
            for k in range(nfold):
                test_g = set(fold_groups[k].tolist())
                test_idx = np.concatenate(
                    [np.arange(bounds[g], bounds[g + 1]) for g in sorted(test_g)]) \
                    if test_g else np.empty(0, np.int64)
                train_idx = np.setdiff1d(np.arange(num_data), test_idx)
                folds.append((train_idx, test_idx))
        elif stratified:
            label = full_data.get_label()
            rng = np.random.RandomState(seed)
            folds = []
            classes = np.unique(label)
            assign = np.empty(num_data, dtype=np.int64)
            for c in classes:
                rows = np.flatnonzero(label == c)
                if shuffle:
                    rng.shuffle(rows)
                assign[rows] = np.arange(len(rows)) % nfold
            for k in range(nfold):
                test_idx = np.flatnonzero(assign == k)
                train_idx = np.flatnonzero(assign != k)
                folds.append((train_idx, test_idx))
        else:
            rng = np.random.RandomState(seed)
            idx = rng.permutation(num_data) if shuffle else np.arange(num_data)
            parts = np.array_split(idx, nfold)
            folds = [(np.setdiff1d(np.arange(num_data), p), np.sort(p))
                     for p in parts]

    ret = CVBooster()
    for train_idx, test_idx in folds:
        train_sub = full_data.subset(np.sort(train_idx))
        valid_sub = full_data.subset(np.sort(test_idx))
        if group is not None:
            bounds = np.concatenate([[0], np.cumsum(group)]).astype(np.int64)
            qid_of_row = np.searchsorted(bounds, np.arange(num_data), side="right") - 1
            tq = qid_of_row[np.sort(train_idx)]
            vq = qid_of_row[np.sort(test_idx)]
            train_sub.group = np.bincount(tq)[np.unique(tq)]
            valid_sub.group = np.bincount(vq)[np.unique(vq)]
        tparams = params
        if fpreproc is not None:
            train_sub, valid_sub, tparams = fpreproc(train_sub, valid_sub,
                                                     copy.deepcopy(params))
        booster = Booster(tparams, train_sub)
        if eval_train_metric:
            booster.add_valid(train_sub, "train")
        booster.add_valid(valid_sub, "valid")
        ret._append(booster)
    return ret


def _agg_cv_result(raw_results, eval_train_metric: bool = False):
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            if eval_train_metric:
                key = f"{one_line[0]} {one_line[1]}"
            else:
                key = one_line[1]
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k], float(np.std(v)))
            for k, v in cvmap.items()]


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       feature_name: str = "auto", categorical_feature: str = "auto",
       early_stopping_rounds: Optional[int] = None, fpreproc=None,
       verbose_eval=None, show_stdv: bool = True, seed: int = 0,
       callbacks=None, eval_train_metric: bool = False,
       return_cvbooster: bool = False):
    """reference engine.py:394."""
    _ensure_jit_cache()
    from .compile import preload_store_async
    preload_store_async()
    params = copy.deepcopy(params) if params else {}
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    early_stopping_rounds = _resolve_early_stopping(params, early_stopping_rounds)
    first_metric_only = params.get("first_metric_only", False)
    if fobj is not None:
        params["objective"] = "none"
    if metrics is not None:
        params["metric"] = metrics
    if isinstance(params.get("objective"), str) and \
            params["objective"] in ("lambdarank", "rank_xendcg"):
        stratified = False

    results = collections.defaultdict(list)
    cvfolds = _make_n_folds(train_set, folds, nfold, params, seed, fpreproc,
                            stratified, shuffle, eval_train_metric)

    cbs = set(callbacks) if callbacks else set()
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(early_stopping_rounds,
                                            first_metric_only, verbose=False))
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and verbose_eval is not False:
        cbs.add(callback_mod.print_evaluation(verbose_eval, show_stdv))
    callbacks_before = {cb for cb in cbs if getattr(cb, "before_iteration", False)}
    callbacks_after = cbs - callbacks_before
    callbacks_before = sorted(callbacks_before, key=lambda cb: getattr(cb, "order", 0))
    callbacks_after = sorted(callbacks_after, key=lambda cb: getattr(cb, "order", 0))

    for i in range(num_boost_round):
        for cb in callbacks_before:
            cb(callback_mod.CallbackEnv(model=cvfolds, params=params,
                                        iteration=i, begin_iteration=0,
                                        end_iteration=num_boost_round,
                                        evaluation_result_list=None))
        for b in cvfolds.boosters:
            b.update(fobj=fobj)
        raw = [b.eval_valid(feval) + (b.eval_train(feval) if eval_train_metric else [])
               for b in cvfolds.boosters]
        raw = [[(n if eval_train_metric else n, m, v, bb) for n, m, v, bb in r]
               for r in raw]
        res = _agg_cv_result(raw, eval_train_metric)
        for _, key, mean, _, std in res:
            results[f"{key}-mean"].append(mean)
            results[f"{key}-stdv"].append(std)
        try:
            for cb in callbacks_after:
                cb(callback_mod.CallbackEnv(model=cvfolds, params=params,
                                            iteration=i, begin_iteration=0,
                                            end_iteration=num_boost_round,
                                            evaluation_result_list=res))
        except callback_mod.EarlyStopException as e:
            cvfolds.best_iteration = e.best_iteration + 1
            for bst in cvfolds.boosters:
                bst.best_iteration = cvfolds.best_iteration
            for k in results:
                results[k] = results[k][:cvfolds.best_iteration]
            break
    out = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvfolds
    return out
