"""Monotone-constraint bookkeeping for the leaf-wise grower.

Host-side port of the reference constraint machinery (reference:
src/treelearner/monotone_constraints.hpp — BasicLeafConstraints :85,
IntermediateLeafConstraints :125, ComputeMonotoneSplitGainPenalty :67).
This logic walks the ~num_leaves-sized tree skeleton, so it stays on
the host (it is O(leaves·depth) pointer chasing, not array math); the
resulting [cmin, cmax] bounds feed the device split scan.

- ``basic``: children of a monotone split are clamped to the midpoint
  of the two outputs; no other leaf is touched.
- ``intermediate``: children are clamped by the actual sibling outputs
  (tighter), and every already-grown leaf CONTIGUOUS with the new
  split (found by walking up from the split and down the opposite
  branches) gets its bound tightened too; those leaves' best splits
  must be recomputed by the caller.
"""
from __future__ import annotations

import math
from typing import List, Tuple

import numpy as np

K_EPSILON = 1e-15


def monotone_penalty_factor(depth: int, penalization: float) -> float:
    """ComputeMonotoneSplitGainPenalty (monotone_constraints.hpp:67)."""
    if penalization >= depth + 1.0:
        return K_EPSILON
    if penalization <= 1.0:
        return 1.0 - penalization / math.pow(2.0, depth) + K_EPSILON
    return 1.0 - math.pow(2.0, penalization - 1.0 - depth) + K_EPSILON


class MonotoneState:
    """Per-tree constraint entries, reset by the grower each tree."""

    def __init__(self, method: str, num_leaves: int,
                 monotone_of_inner: np.ndarray) -> None:
        self.method = method
        self.num_leaves = num_leaves
        self.monotone = monotone_of_inner
        self.cmin = np.full(num_leaves, -np.inf)
        self.cmax = np.full(num_leaves, np.inf)
        self.node_parent = np.full(max(num_leaves - 1, 1), -1, np.int32)
        self.in_monotone_subtree = np.zeros(num_leaves, bool)

    # -- hooks ----------------------------------------------------------
    def before_split(self, tree, leaf: int, mono_type: int) -> None:
        """Must run BEFORE tree.split (records the pre-split parent;
        reference BeforeSplit, :141)."""
        if self.method != "intermediate":
            return
        new_leaf = tree.num_leaves
        if mono_type != 0 or self.in_monotone_subtree[leaf]:
            self.in_monotone_subtree[leaf] = True
            self.in_monotone_subtree[new_leaf] = True
        self.node_parent[new_leaf - 1] = tree.leaf_parent[leaf]

    def update(self, tree, leaf: int, new_leaf: int, mono_type: int,
               is_numerical: bool, left_output: float, right_output: float,
               split_feature_inner: int, split_threshold: int,
               leaf_has_candidate) -> List[int]:
        """Runs AFTER tree.split; tightens the two children's entries
        and (intermediate) returns other leaf ids whose bounds changed
        (reference Update, :85-116 basic / :170-200 intermediate)."""
        self.cmin[new_leaf] = self.cmin[leaf]
        self.cmax[new_leaf] = self.cmax[leaf]
        if not is_numerical:
            return []
        if self.method != "intermediate":
            if mono_type != 0:
                mid = (left_output + right_output) / 2.0
                if mono_type < 0:
                    self.cmin[leaf] = max(self.cmin[leaf], mid)
                    self.cmax[new_leaf] = min(self.cmax[new_leaf], mid)
                else:
                    self.cmax[leaf] = min(self.cmax[leaf], mid)
                    self.cmin[new_leaf] = max(self.cmin[new_leaf], mid)
            return []

        if not self.in_monotone_subtree[leaf]:
            return []
        # children tightened by the sibling's actual output (:155-168)
        if mono_type < 0:
            self.cmin[leaf] = max(self.cmin[leaf], right_output)
            self.cmax[new_leaf] = min(self.cmax[new_leaf], left_output)
        elif mono_type > 0:
            self.cmax[leaf] = min(self.cmax[leaf], right_output)
            self.cmin[new_leaf] = max(self.cmin[new_leaf], left_output)

        self._to_update: List[int] = []
        self._feat_up: List[int] = []
        self._thr_up: List[int] = []
        self._was_right: List[bool] = []
        self._go_up(tree, tree.leaf_parent[new_leaf], split_feature_inner,
                    split_threshold, left_output, right_output,
                    leaf_has_candidate)
        return self._to_update

    # -- the contiguity walk (GoUpToFindLeavesToUpdate, :234) -----------
    def _go_up(self, tree, node_idx: int, split_feature: int,
               split_threshold: int, left_output: float, right_output: float,
               leaf_has_candidate) -> None:
        parent = int(self.node_parent[node_idx])
        if parent < 0:
            return
        inner = int(tree.split_feature_inner[parent])
        mono = int(self.monotone[inner]) if inner < len(self.monotone) else 0
        is_right = int(tree.right_child[parent]) == node_idx
        is_numerical = (tree.decision_type[parent] & 1) == 0

        opposite_should_update = True
        if is_numerical:
            for f_up, was_r in zip(self._feat_up, self._was_right):
                if f_up == inner and was_r == is_right:
                    opposite_should_update = False
                    break

        if opposite_should_update:
            if mono != 0:
                left_idx = int(tree.left_child[parent])
                right_idx = int(tree.right_child[parent])
                cur_is_left = left_idx == node_idx
                opposite = right_idx if cur_is_left else left_idx
                update_max = cur_is_left if mono < 0 else not cur_is_left
                self._go_down(tree, opposite, update_max, split_feature,
                              split_threshold, left_output, right_output,
                              True, True, leaf_has_candidate)
            self._was_right.append(is_right)
            self._thr_up.append(int(tree.threshold_in_bin[parent]))
            self._feat_up.append(inner)

        self._go_up(tree, parent, split_feature, split_threshold,
                    left_output, right_output, leaf_has_candidate)

    def _go_down(self, tree, node_idx: int, update_max: bool,
                 split_feature: int, split_threshold: int,
                 left_output: float, right_output: float,
                 use_left: bool, use_right: bool, leaf_has_candidate) -> None:
        """GoDownToFindLeavesToUpdate (:310)."""
        if node_idx < 0:
            leaf_idx = ~node_idx
            if not leaf_has_candidate(leaf_idx):
                return
            if use_left and use_right:
                lo, hi = sorted((left_output, right_output))
            elif use_right:
                lo = hi = right_output
            else:
                lo = hi = left_output
            changed = False
            if not update_max:
                if hi > self.cmin[leaf_idx]:
                    self.cmin[leaf_idx] = hi
                    changed = True
            else:
                if lo < self.cmax[leaf_idx]:
                    self.cmax[leaf_idx] = lo
                    changed = True
            if changed and leaf_idx not in self._to_update:
                self._to_update.append(leaf_idx)
            return

        keep_left, keep_right = self._keep_going(tree, node_idx)
        inner = int(tree.split_feature_inner[node_idx])
        thr = int(tree.threshold_in_bin[node_idx])
        is_numerical = (tree.decision_type[node_idx] & 1) == 0
        use_left_for_right = True
        use_right_for_left = True
        if is_numerical and inner == split_feature:
            if thr >= split_threshold:
                use_left_for_right = False
            if thr <= split_threshold:
                use_right_for_left = False
        if keep_left:
            self._go_down(tree, int(tree.left_child[node_idx]), update_max,
                          split_feature, split_threshold, left_output,
                          right_output, use_left,
                          use_right_for_left and use_right, leaf_has_candidate)
        if keep_right:
            self._go_down(tree, int(tree.right_child[node_idx]), update_max,
                          split_feature, split_threshold, left_output,
                          right_output, use_left_for_right and use_left,
                          use_right, leaf_has_candidate)

    def _keep_going(self, tree, node_idx: int) -> Tuple[bool, bool]:
        """ShouldKeepGoingLeftRight (:423)."""
        inner = int(tree.split_feature_inner[node_idx])
        thr = int(tree.threshold_in_bin[node_idx])
        is_numerical = (tree.decision_type[node_idx] & 1) == 0
        keep_left = keep_right = True
        if is_numerical:
            for f_up, t_up, was_r in zip(self._feat_up, self._thr_up,
                                         self._was_right):
                if f_up != inner:
                    continue
                if thr >= t_up and not was_r:
                    keep_right = False
                if thr <= t_up and was_r:
                    keep_left = False
                if not keep_left and not keep_right:
                    break
        return keep_left, keep_right
