"""Fully on-device leaf-wise tree growth — one dispatch per iteration.

This is the TPU-critical redesign of the training hot path. The
reference's per-split control flow (serial_tree_learner.cpp:152-202)
costs it nothing on CPU, and its GPU learner tolerates a PCIe sync per
leaf (gpu_tree_learner.cpp). Here every host→device round trip costs
~100 ms over the accelerator tunnel, so num_leaves-1 split steps per
tree MUST run inside one compiled program:

- The whole split loop is a `lax.while_loop`; per-leaf state (ranges,
  sums, outputs, best-split records, the histogram pool) lives in
  fixed-size [num_leaves] device arrays — the HistogramPool
  (feature_histogram.hpp:1061) becomes a dense [L, F, B, 2] pool.
- DataPartition::Split becomes a full-length masked-cumsum stable
  partition (no sort): new positions are prefix sums of the left/right
  predicates inside the leaf's window, identity outside — O(N) per
  split, one scatter.
- Leaf histograms use `lax.switch` over power-of-two capacity buckets,
  giving the smaller-child gather dynamic cost under static shapes;
  the larger child is histogram subtraction, as in the reference
  (:396-404).
- Gradients, the tree build, shrinkage and the score update all fuse
  into the same program, so an iteration with no evaluation requires
  ZERO synchronous host transfers — trees come back as device arrays
  materialized lazily.

Coverage: numerical features, serial learner, any objective without
leaf renewal, bagging via a host-provided permutation, per-tree
feature_fraction, max_depth, basic monotone constraints, L1/L2/
max_delta_step/path smoothing. Categorical features, forced splits,
interaction constraints, feature_fraction_bynode, CEGB and
renew-tree-output objectives fall back to the host-loop grower
(treelearner/serial.py).
"""
from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config
from ..io.dataset import BinnedDataset
from ..io.binning import BIN_CATEGORICAL
from ..models.tree import Tree
from ..ops import histogram as H
from ..ops import split as S
from ..utils import log

NEG_INF = jnp.float32(-jnp.inf)


def fused_supported(config: Config, dataset: BinnedDataset,
                    objective) -> bool:
    """Static eligibility check for the fused path."""
    if config.tree_learner != "serial":
        return False
    if any(m.bin_type == BIN_CATEGORICAL for m in dataset.bin_mappers):
        return False
    if config.forcedsplits_filename or config.interaction_constraints:
        return False
    if config.feature_fraction_bynode < 1.0 or config.extra_trees:
        return False
    if (config.cegb_tradeoff != 1.0 or config.cegb_penalty_split > 0
            or config.cegb_penalty_feature_coupled
            or config.cegb_penalty_feature_lazy):
        return False
    if config.monotone_constraints and (
            config.monotone_constraints_method != "basic"
            or config.monotone_penalty > 0):
        # intermediate mode re-searches arbitrary leaves after a split —
        # host-loop territory (treelearner/monotone.py)
        return False
    if objective is not None and objective.is_renew_tree_output:
        return False
    if dataset.num_features == 0:
        return False
    return True


class FusedTreeState(NamedTuple):
    """Loop-carried device state; [L] = num_leaves slots."""
    data: jax.Array            # [N, W] leaf-ordered packed rows (u8)
    n_leaves: jax.Array        # scalar i32
    leaf_start: jax.Array      # [L]
    leaf_count: jax.Array      # [L]
    leaf_sum_g: jax.Array      # [L]
    leaf_sum_h: jax.Array      # [L]
    leaf_output: jax.Array     # [L]
    leaf_depth: jax.Array      # [L]
    leaf_parent: jax.Array     # [L]
    leaf_cmin: jax.Array       # [L] monotone lower bound
    leaf_cmax: jax.Array       # [L]
    # per-leaf best split record
    best_gain: jax.Array       # [L] (-inf = unsplittable)
    best_feature: jax.Array    # [L]
    best_thr: jax.Array        # [L]
    best_dl: jax.Array         # [L] bool
    best_lg: jax.Array         # [L]
    best_lh: jax.Array         # [L]
    best_lcnt: jax.Array       # [L]
    best_lout: jax.Array       # [L]
    best_rg: jax.Array         # [L]
    best_rh: jax.Array         # [L]
    best_rcnt: jax.Array       # [L]
    best_rout: jax.Array       # [L]
    hist_pool: jax.Array       # [L, F, B, 2]
    # tree under construction (internal nodes [L-1])
    t_feature: jax.Array
    t_thr: jax.Array
    t_dl: jax.Array
    t_left: jax.Array
    t_right: jax.Array
    t_gain: jax.Array
    t_ivalue: jax.Array
    t_iweight: jax.Array
    t_icount: jax.Array


class FusedSerialGrower:
    """Builds and owns the single-dispatch training-iteration program."""

    def __init__(self, dataset: BinnedDataset, config: Config) -> None:
        self.dataset = dataset
        self.config = config
        self.bins = dataset.device_bins()
        self.num_features = dataset.num_features
        mappers = dataset.bin_mappers
        self.max_num_bin = max((m.num_bin for m in mappers), default=2)
        self.num_leaves = max(config.num_leaves, 2)
        monotone = [dataset.monotone_constraint(i)
                    for i in range(self.num_features)]
        self.use_monotone = any(m != 0 for m in monotone)
        penalty = list(config.feature_contri) + \
            [1.0] * (self.num_features - len(config.feature_contri))
        self.meta = S.FeatureMeta.build(
            num_bin=[m.num_bin for m in mappers],
            missing_type=[m.missing_type for m in mappers],
            default_bin=[m.default_bin for m in mappers],
            is_categorical=[False] * self.num_features,
            monotone=monotone,
            penalty=[float(p) for p in penalty[:self.num_features]])
        self.split_cfg = S.SplitConfig(
            lambda_l1=config.lambda_l1, lambda_l2=config.lambda_l2,
            min_data_in_leaf=config.min_data_in_leaf,
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            max_delta_step=config.max_delta_step,
            path_smooth=config.path_smooth,
            use_monotone=self.use_monotone)
        self.feature_miss_bin = jnp.asarray([
            (m.num_bin - 1 if m.missing_type == 2 else
             (m.default_bin if m.missing_type == 1 else -1))
            for m in mappers], dtype=jnp.int32)
        # EFB bundle views (None on dense/trivial datasets)
        self._efb_dev = dataset.device_bundle_tables()
        self._efb_hist = dataset.device_hist_tables()
        self.group_max_bin = dataset.group_max_bins
        # TPU: the pallas NT-radix kernel; bfloat16 inputs are the
        # default (the reference GPU learner's single-precision
        # histograms, gpu_use_dp=false — AUC-neutral, 2x MXU rate).
        # Other backends keep the scatter path (exact oracle).
        if jax.default_backend() == "tpu":
            self._hist_method = ("radix_pallas"
                                 if config.tpu_hist_dtype == "float32"
                                 else "radix_pallas_bf16")
        else:
            self._hist_method = None
        # leaf-ordered packed row layout: [G*cb bin-code bytes | 8 bytes
        # f32 (grad, hess) | 4 bytes i32 original row id]. TPU random
        # row gathers/scatters run at ~10ns/row regardless of width, so
        # the whole training row travels as ONE descriptor during the
        # partition scatter and every histogram READ is a contiguous
        # dynamic_slice at HBM speed (see _split_step).
        self._num_cols = int(self.bins.shape[1])
        self._code_bytes = int(np.dtype(self.bins.dtype).itemsize)
        self._row_width = self._num_cols * self._code_bytes + 12
        self._code_bytes_dev = None  # built lazily on first grow
        # histogram_pool_size (MB; <=0 unlimited — reference
        # feature_histogram.hpp:1061 HistogramPool): when the dense
        # [L, F, B, 2] pool would not fit, run pool-less — both
        # children's histograms are computed directly (no subtraction),
        # nothing is cached, memory is O(F*B) instead of O(L*F*B)
        pool_mb = config.histogram_pool_size
        need = (self.num_leaves * self.num_features
                * self.max_num_bin * 2 * 4)
        self._use_hist_pool = pool_mb <= 0 or need <= pool_mb * 1024 * 1024
        if not self._use_hist_pool:
            log.info("histogram pool (%.0f MB) exceeds histogram_pool_size"
                     "=%.0f MB: disabling histogram subtraction",
                     need / 1e6, pool_mb)

        # score updates can reuse the partition's leaf assignment only
        # when every scored row is in-bag (no bagging/GOSS/RF); with
        # bagging the out-of-bag rows are never partitioned and the
        # fallback is the tree re-traversal
        bag_active = (
            (config.bagging_freq > 0
             and (config.bagging_fraction < 1.0
                  or config.pos_bagging_fraction < 1.0
                  or config.neg_bagging_fraction < 1.0))
            or config.boosting in ("goss", "rf"))
        self._score_from_partition = not bag_active

        self._col_rng = np.random.RandomState(config.feature_fraction_seed)
        n = dataset.num_data
        # capacity ladder for the lax.switch histogram/partition
        # branches. Each branch duplicates the full kernel in the
        # compiled program, so XLA compile time grows with the ladder
        # size — factor 4 keeps it at ~log4(N) branches (5 at 1M rows
        # vs 13 for factor 2) for at most 4x padded work on mid-size
        # leaves (the dominant root/early splits sit in the top bucket
        # either way, and the smaller-child trick bounds the rest).
        self._caps = []
        c = 4096
        while c < n:
            self._caps.append(c)
            c *= 4
        # top bucket is exactly n: the next power of four would pad the
        # root splits by up to 1.6x (measured 10.5M -> 16.7M at HIGGS)
        self._caps.append(n)
        self._grow_jit = jax.jit(self._grow_tree,
                                 static_argnames=("compute_score_update",))

    # ------------------------------------------------------------------
    def _switch_by_cap(self, count, branches_of_cap, *args):
        branches = [branches_of_cap(c) for c in self._caps]
        cap_arr = jnp.asarray(self._caps, jnp.int32)
        idx = jnp.searchsorted(cap_arr, jnp.maximum(count, 1))
        idx = jnp.minimum(idx, len(self._caps) - 1)
        return jax.lax.switch(idx, branches, *args)

    def _window_hist(self, b, g, h):
        """Histogram of an already-loaded bin block with masked weights;
        EFB bundle columns are gathered back to per-feature space
        (FixHistogram mfb reconstruction)."""
        if self._efb_hist is None:
            return H.histogram(b, g, h, self.max_num_bin,
                               method=self._hist_method)
        from ..io.efb import per_feature_hist
        ghist = H.histogram(b, g, h, self.group_max_bin,
                            method=self._hist_method)
        total = ghist[0].sum(axis=0)
        return per_feature_hist(ghist, self._efb_hist, total[0], total[1])

    # -- leaf-ordered packed rows --------------------------------------
    def code_bytes_dev(self):
        """[N, G*cb] uint8 bin-code bytes, built once. Passed to the
        jitted tree builder as an ARGUMENT — a closure capture would
        embed the full matrix as an HLO constant (294 MB at HIGGS
        scale, which overflows remote-compile request limits)."""
        if self._code_bytes_dev is None:
            b = self.bins
            if self._code_bytes > 1:
                b = jax.lax.bitcast_convert_type(b, jnp.uint8).reshape(
                    b.shape[0], self._num_cols * self._code_bytes)
            self._code_bytes_dev = b
        return self._code_bytes_dev

    def _pack_rows(self, codes_bytes, perm0, gh2):
        """[N, W] uint8 leaf-ordered training rows (bin-code bytes +
        f32 grad/hess bytes + i32 row-id bytes). Without bagging the
        initial leaf order IS row order, so the pack is a contiguous
        concat (no gather); with bagging it costs one row gather per
        tree instead of one per split."""
        n = perm0.shape[0]
        gh_b = jax.lax.bitcast_convert_type(
            gh2.astype(jnp.float32), jnp.uint8).reshape(n, 8)
        row_b = jax.lax.bitcast_convert_type(
            perm0.astype(jnp.int32), jnp.uint8)
        if self._score_from_partition:  # perm0 == arange
            return jnp.concatenate([codes_bytes, gh_b, row_b], axis=1)
        return jnp.concatenate(
            [codes_bytes[perm0], gh_b[perm0], row_b], axis=1)

    def _unpack_block(self, block):
        """[cap, W] u8 -> (codes [cap, G] int, gh [cap, 2] f32)."""
        cap = block.shape[0]
        G, cb = self._num_cols, self._code_bytes
        if cb == 1:
            codes = block[:, :G]
        else:
            codes = jax.lax.bitcast_convert_type(
                block[:, :G * cb].reshape(cap, G, cb), jnp.uint16)
        gh = jax.lax.bitcast_convert_type(
            block[:, G * cb:G * cb + 8].reshape(cap, 2, 4), jnp.float32)
        return codes, gh

    def _row_ids(self, data):
        return jax.lax.bitcast_convert_type(data[:, -4:], jnp.int32)

    def _read_window(self, data, start, count, cap):
        """Contiguous [cap, W] window covering [start, start+count);
        returns (block, valid, read_start). The capacity ladder tops out
        at exactly N, so cap <= N always."""
        n = data.shape[0]
        assert cap <= n, "capacity ladder must top out at num_data"
        start = jnp.asarray(start, jnp.int32)
        read_start = jnp.minimum(start, n - cap)
        block = jax.lax.dynamic_slice(
            data, (read_start, 0), (cap, data.shape[1]))
        off = start - read_start
        pos = jnp.arange(cap, dtype=jnp.int32)
        valid = (pos >= off) & (pos < off + count)
        return block, valid, read_start

    def _leaf_hist_switch(self, data, start, count):
        """Histogram of a leaf range: a contiguous slice of the
        leaf-ordered rows + masked radix matmul — no gather at all."""
        def branch(cap):
            def fn(data, start, count):
                block, valid, _ = self._read_window(data, start, count, cap)
                codes, gh = self._unpack_block(block)
                g = jnp.where(valid, gh[:, 0], 0.0)
                h = jnp.where(valid, gh[:, 1], 0.0)
                return self._window_hist(codes, g, h)
            return fn

        return self._switch_by_cap(count, branch, data, start, count)

    def _split_step(self, data, start, count, feature, thr, dl, miss_bin):
        """Split one leaf: ONE contiguous read of its row block, the
        routing decision, a single row-scatter writing the partitioned
        block back, and the smaller child's histogram from the same
        block. This is the TPU answer to DataPartition::Split +
        ConstructHistograms: random access is concentrated in one
        in-window row scatter (~10ns/row); everything else is
        slice-contiguous. Returns (data, nleft, hist_smaller)."""
        efb = self._efb_dev

        def branch(cap):
            def fn(data, start, count, feature, thr, dl, miss_bin):
                n = data.shape[0]
                block, valid, read_start = self._read_window(
                    data, start, count, cap)
                codes, gh = self._unpack_block(block)

                # --- routing on the split column. The column pick is a
                # one-hot matmul, NOT take_along_axis: a traced column
                # index lowers to a per-row gather (~7ns/row — measured
                # as the single hottest op of the old split step) while
                # the [cap, G] @ [G] product rides the MXU for free ---
                gidx = efb[0][feature] if efb is not None else feature
                sel = (jnp.arange(codes.shape[1]) == gidx).astype(jnp.float32)
                col = jnp.einsum(
                    "rg,g->r", codes.astype(jnp.float32), sel,
                    precision="highest").astype(jnp.int32)
                if efb is not None:
                    from ..io.efb import decode_bins
                    binval = decode_bins(col, feature, efb)
                else:
                    binval = col
                from ..ops.partition import _decision_go_left
                go_left = _decision_go_left(binval, thr, dl, miss_bin,
                                            jnp.bool_(False))

                # --- stable partition: argsort of the 4-way key gives
                # the inverse permutation directly (pre-window rows
                # first in original order, then lefts, rights, tail) —
                # no scatter at all; TPU scatters (even 4-byte ones)
                # degrade badly beyond ~2M-row tables, sorts don't ---
                pos = jnp.arange(cap, dtype=jnp.int32)
                off = jnp.asarray(start, jnp.int32) - read_start
                gl = go_left & valid
                gr = (~go_left) & valid
                nleft = jnp.sum(gl).astype(jnp.int32)
                key = jnp.where(pos < off, jnp.int8(0),
                                jnp.where(gl, jnp.int8(1),
                                          jnp.where(gr, jnp.int8(2),
                                                    jnp.int8(3))))
                inv = jnp.argsort(key, stable=True)
                # row gathers run ~11 ns/row for <=1M-row blocks and
                # ~37 ns/row beyond (source-table size bound; chunking
                # the index stream was measured neutral)
                new_block = block[inv]
                data = jax.lax.dynamic_update_slice(
                    data, new_block, (read_start, 0))
                return data, nleft
            return fn

        data, nleft = self._switch_by_cap(count, branch, data, start, count,
                                          feature, thr, dl, miss_bin)
        # smaller child's histogram at ITS OWN capacity bucket — the
        # post-partition child range is a contiguous slice, and the
        # pallas matmul volume halves vs histogramming the parent block
        left_smaller = nleft <= count - nleft
        s_start = jnp.where(left_smaller, start, start + nleft)
        s_count = jnp.where(left_smaller, nleft, count - nleft)
        hist_small = self._leaf_hist_switch(data, s_start, s_count)
        return data, nleft, hist_small

    def _scan_leaf(self, hist, sum_g, sum_h, count, output, cmin, cmax,
                   feature_mask):
        """Best split of one leaf from its pooled histogram."""
        res = S.numerical_split_scan(hist, self.meta, self.split_cfg,
                                     sum_g, sum_h, count, output, cmin, cmax)
        gains = jnp.where(feature_mask, res["gain"], S.K_MIN_SCORE)
        f = jnp.argmax(gains).astype(jnp.int32)
        g = gains[f]
        ok = jnp.isfinite(g) & (g > 0.0) \
            & (count >= 2 * self.split_cfg.min_data_in_leaf)
        return dict(
            gain=jnp.where(ok, g, NEG_INF),
            feature=f,
            thr=res["threshold"][f],
            dl=res["default_left"][f],
            lg=res["left_sum_gradient"][f], lh=res["left_sum_hessian"][f],
            lcnt=res["left_count"][f], lout=res["left_output"][f],
            rg=res["right_sum_gradient"][f], rh=res["right_sum_hessian"][f],
            rcnt=res["right_count"][f], rout=res["right_output"][f])

    def _scan_two_leaves(self, hist2, sum_g2, sum_h2, count2, output2,
                         cmin2, cmax2, feature_mask):
        """Both children's best splits from one vmapped scan (halves the
        per-split scan kernel count vs two sequential _scan_leaf calls)."""
        res2 = jax.vmap(
            lambda h, sg, sh, c, o, lo, hi: self._scan_leaf(
                h, sg, sh, c, o, lo, hi, feature_mask)
        )(hist2, sum_g2, sum_h2, count2, output2, cmin2, cmax2)
        first = {k: v[0] for k, v in res2.items()}
        second = {k: v[1] for k, v in res2.items()}
        return first, second

    # ------------------------------------------------------------------
    def _grow_tree(self, codes_bytes, grad, hess, perm0, bag_cnt,
                   feature_mask,
                   compute_score_update: bool = True):
        """The single-dispatch tree builder. Returns (tree arrays dict,
        leaf_value_update [N] or None)."""
        L = self.num_leaves
        F, B = self.num_features, self.max_num_bin
        n = perm0.shape[0]
        f32, i32 = jnp.float32, jnp.int32
        gh2 = jnp.stack([grad, hess], axis=1)
        data0 = self._pack_rows(codes_bytes, perm0, gh2)

        root_hist = self._leaf_hist_switch(data0, jnp.int32(0), bag_cnt)
        sum_g = jnp.sum(root_hist[0, :, 0])
        sum_h = jnp.sum(root_hist[0, :, 1])
        root_best = self._scan_leaf(root_hist, sum_g, sum_h, bag_cnt,
                                    f32(0.0), f32(-jnp.inf), f32(jnp.inf),
                                    feature_mask)

        def arr(val, dtype=f32):
            return jnp.full((L,), val, dtype)

        st = FusedTreeState(
            data=data0, n_leaves=i32(1),
            leaf_start=arr(0, i32).at[0].set(0),
            leaf_count=arr(0, i32).at[0].set(bag_cnt),
            leaf_sum_g=arr(0.0).at[0].set(sum_g),
            leaf_sum_h=arr(0.0).at[0].set(sum_h),
            leaf_output=arr(0.0),
            leaf_depth=arr(0, i32),
            leaf_parent=arr(-1, i32),
            leaf_cmin=arr(-jnp.inf), leaf_cmax=arr(jnp.inf),
            best_gain=arr(NEG_INF).at[0].set(root_best["gain"]),
            best_feature=arr(0, i32).at[0].set(root_best["feature"]),
            best_thr=arr(0, i32).at[0].set(root_best["thr"]),
            best_dl=arr(False, bool).at[0].set(root_best["dl"]),
            best_lg=arr(0.0).at[0].set(root_best["lg"]),
            best_lh=arr(0.0).at[0].set(root_best["lh"]),
            best_lcnt=arr(0, i32).at[0].set(root_best["lcnt"]),
            best_lout=arr(0.0).at[0].set(root_best["lout"]),
            best_rg=arr(0.0).at[0].set(root_best["rg"]),
            best_rh=arr(0.0).at[0].set(root_best["rh"]),
            best_rcnt=arr(0, i32).at[0].set(root_best["rcnt"]),
            best_rout=arr(0.0).at[0].set(root_best["rout"]),
            hist_pool=(jnp.zeros((L, F, B, 2), f32).at[0].set(root_hist)
                       if self._use_hist_pool
                       else jnp.zeros((1, 1, 1, 2), f32)),
            t_feature=jnp.zeros((L - 1,), i32),
            t_thr=jnp.zeros((L - 1,), i32),
            t_dl=jnp.zeros((L - 1,), bool),
            t_left=jnp.zeros((L - 1,), i32),
            t_right=jnp.zeros((L - 1,), i32),
            t_gain=jnp.zeros((L - 1,), f32),
            t_ivalue=jnp.zeros((L - 1,), f32),
            t_iweight=jnp.zeros((L - 1,), f32),
            t_icount=jnp.zeros((L - 1,), i32),
        )

        max_depth = self.config.max_depth
        mono_dev = self.meta.monotone

        def cond(st: FusedTreeState):
            gains = st.best_gain
            if max_depth > 0:
                gains = jnp.where(st.leaf_depth >= max_depth, NEG_INF, gains)
            return (st.n_leaves < L) & (jnp.max(gains) > 0.0)

        def body(st: FusedTreeState) -> FusedTreeState:
            gains = st.best_gain
            if max_depth > 0:
                gains = jnp.where(st.leaf_depth >= max_depth, NEG_INF, gains)
            leaf = jnp.argmax(gains).astype(i32)
            node = st.n_leaves - 1
            new_leaf = st.n_leaves

            feat = st.best_feature[leaf]
            thr = st.best_thr[leaf]
            dl = st.best_dl[leaf]
            miss = self.feature_miss_bin[feat]

            # --- tree bookkeeping (Tree::Split semantics, tree.h:61) ---
            parent = st.leaf_parent[leaf]
            has_parent = parent >= 0
            pl = st.t_left[jnp.maximum(parent, 0)]
            fix_left = has_parent & (pl == ~leaf)
            t_left = st.t_left.at[jnp.maximum(parent, 0)].set(
                jnp.where(fix_left, node, st.t_left[jnp.maximum(parent, 0)]))
            t_right = st.t_right.at[jnp.maximum(parent, 0)].set(
                jnp.where(has_parent & ~fix_left, node,
                          st.t_right[jnp.maximum(parent, 0)]))
            t_feature = st.t_feature.at[node].set(feat)
            t_thr = st.t_thr.at[node].set(thr)
            t_dl = st.t_dl.at[node].set(dl)
            t_left = t_left.at[node].set(~leaf)
            t_right = t_right.at[node].set(~new_leaf)
            t_gain = st.t_gain.at[node].set(st.best_gain[leaf])
            t_ivalue = st.t_ivalue.at[node].set(st.leaf_output[leaf])
            t_iweight = st.t_iweight.at[node].set(st.leaf_sum_h[leaf])
            t_icount = st.t_icount.at[node].set(st.leaf_count[leaf])

            # --- partition + smaller-child histogram (one block) ---
            start = st.leaf_start[leaf]
            count = st.leaf_count[leaf]
            new_data, nleft, hist_small = self._split_step(
                st.data, start, count, feat, thr, dl, miss)
            nright = count - nleft

            # --- children bookkeeping ---
            lout, rout = st.best_lout[leaf], st.best_rout[leaf]
            depth = st.leaf_depth[leaf] + 1
            cmin, cmax = st.leaf_cmin[leaf], st.leaf_cmax[leaf]
            if self.use_monotone:
                monof = mono_dev[feat]
                mid = (lout + rout) / 2.0
                lcmax = jnp.where(monof > 0, jnp.minimum(cmax, mid), cmax)
                rcmin = jnp.where(monof > 0, jnp.maximum(cmin, mid), cmin)
                lcmin = jnp.where(monof < 0, jnp.maximum(cmin, mid), cmin)
                rcmax = jnp.where(monof < 0, jnp.minimum(cmax, mid), cmax)
            else:
                lcmin, lcmax, rcmin, rcmax = cmin, cmax, cmin, cmax

            leaf_start = st.leaf_start.at[new_leaf].set(start + nleft)
            leaf_count = st.leaf_count.at[leaf].set(nleft)\
                                       .at[new_leaf].set(nright)
            leaf_sum_g = st.leaf_sum_g.at[leaf].set(st.best_lg[leaf])\
                                      .at[new_leaf].set(st.best_rg[leaf])
            leaf_sum_h = st.leaf_sum_h.at[leaf].set(st.best_lh[leaf])\
                                      .at[new_leaf].set(st.best_rh[leaf])
            leaf_output = st.leaf_output.at[leaf].set(lout)\
                                        .at[new_leaf].set(rout)
            leaf_depth = st.leaf_depth.at[leaf].set(depth)\
                                      .at[new_leaf].set(depth)
            leaf_parent = st.leaf_parent.at[leaf].set(node)\
                                        .at[new_leaf].set(node)
            leaf_cmin = st.leaf_cmin.at[leaf].set(lcmin).at[new_leaf].set(rcmin)
            leaf_cmax = st.leaf_cmax.at[leaf].set(lcmax).at[new_leaf].set(rcmax)

            # --- larger child: subtraction from the pooled parent (or a
            # second contiguous-slice histogram when pool-less) ---
            left_smaller = nleft <= nright
            if self._use_hist_pool:
                hist_large = st.hist_pool[leaf] - hist_small
                hist_left = jnp.where(left_smaller, hist_small, hist_large)
                hist_right = jnp.where(left_smaller, hist_large, hist_small)
                hist_pool = st.hist_pool.at[leaf].set(hist_left)\
                                        .at[new_leaf].set(hist_right)
            else:
                l_start = jnp.where(left_smaller, start + nleft, start)
                l_count = jnp.where(left_smaller, nright, nleft)
                hist_large = self._leaf_hist_switch(new_data, l_start,
                                                    l_count)
                hist_left = jnp.where(left_smaller, hist_small, hist_large)
                hist_right = jnp.where(left_smaller, hist_large, hist_small)
                hist_pool = st.hist_pool

            # --- best splits for both children (one vmapped scan) ---
            bl, br = self._scan_two_leaves(
                jnp.stack([hist_left, hist_right]),
                jnp.stack([st.best_lg[leaf], st.best_rg[leaf]]),
                jnp.stack([st.best_lh[leaf], st.best_rh[leaf]]),
                jnp.stack([nleft, nright]),
                jnp.stack([lout, rout]),
                jnp.stack([lcmin, rcmin]),
                jnp.stack([lcmax, rcmax]), feature_mask)

            def upd(a, key, cast=lambda x: x):
                return a.at[leaf].set(cast(bl[key])).at[new_leaf].set(cast(br[key]))

            return FusedTreeState(
                data=new_data, n_leaves=st.n_leaves + 1,
                leaf_start=leaf_start, leaf_count=leaf_count,
                leaf_sum_g=leaf_sum_g, leaf_sum_h=leaf_sum_h,
                leaf_output=leaf_output, leaf_depth=leaf_depth,
                leaf_parent=leaf_parent, leaf_cmin=leaf_cmin,
                leaf_cmax=leaf_cmax,
                best_gain=upd(st.best_gain, "gain"),
                best_feature=upd(st.best_feature, "feature"),
                best_thr=upd(st.best_thr, "thr"),
                best_dl=upd(st.best_dl, "dl"),
                best_lg=upd(st.best_lg, "lg"), best_lh=upd(st.best_lh, "lh"),
                best_lcnt=upd(st.best_lcnt, "lcnt"),
                best_lout=upd(st.best_lout, "lout"),
                best_rg=upd(st.best_rg, "rg"), best_rh=upd(st.best_rh, "rh"),
                best_rcnt=upd(st.best_rcnt, "rcnt"),
                best_rout=upd(st.best_rout, "rout"),
                hist_pool=hist_pool,
                t_feature=t_feature, t_thr=t_thr, t_dl=t_dl, t_left=t_left,
                t_right=t_right, t_gain=t_gain, t_ivalue=t_ivalue,
                t_iweight=t_iweight, t_icount=t_icount,
            )

        st = jax.lax.while_loop(cond, body, st)

        tree_arrays = dict(
            n_leaves=st.n_leaves,
            split_feature=st.t_feature, threshold_bin=st.t_thr,
            default_left=st.t_dl, left_child=st.t_left, right_child=st.t_right,
            split_gain=st.t_gain, internal_value=st.t_ivalue,
            internal_weight=st.t_iweight, internal_count=st.t_icount,
            leaf_value=st.leaf_output, leaf_weight=st.leaf_sum_h,
            leaf_count=st.leaf_count, leaf_depth=st.leaf_depth,
        )

        leaf_of_row = None
        if compute_score_update:
            if self._score_from_partition:
                # the partition already assigned every row to a leaf:
                # leaf intervals [start, start+count) tile [0, N), so a
                # searchsorted over the sorted starts + a scatter through
                # the row ids yields leaf-of-row without re-walking
                # the tree (the DataPartition shortcut of the reference's
                # ScoreUpdater::AddScore, score_updater.hpp:88 — here it
                # replaces an ~O(depth) gather chain per iteration)
                leaf_of_row = self._leaf_ids_from_partition(st, n)
            else:
                # bagging: re-walk the tree over the ROW-ORDERED bins,
                # reconstructed from the code bytes arg (a self.bins
                # closure would embed the matrix as an HLO constant)
                bins_mat = codes_bytes
                if self._code_bytes > 1:
                    bins_mat = jax.lax.bitcast_convert_type(
                        codes_bytes.reshape(n, self._num_cols,
                                            self._code_bytes), jnp.uint16)
                leaf_of_row = self.traverse_bins(tree_arrays, bins_mat)
        return tree_arrays, leaf_of_row

    def _leaf_ids_from_partition(self, st: FusedTreeState, n: int):
        L = self.num_leaves
        lid = jnp.arange(L, dtype=jnp.int32)
        valid = lid < st.n_leaves
        starts = jnp.where(valid, st.leaf_start, jnp.int32(n) + 1)
        order = jnp.argsort(starts)             # tiny: [num_leaves]
        sorted_starts = starts[order]
        pos = jnp.arange(n, dtype=jnp.int32)
        # rank of each position among the sorted starts as a broadcast
        # compare-and-sum ([N, L] fused on the VPU) — jnp.searchsorted
        # binary-search gathers cost ~8 passes of per-element access
        k = jnp.sum(pos[:, None] >= sorted_starts[None, :],
                    axis=1).astype(jnp.int32) - 1
        pos_leaf = order[jnp.maximum(k, 0)]
        row_ids = self._row_ids(st.data)
        return jnp.zeros(n, jnp.int32).at[row_ids].set(pos_leaf,
                                                       unique_indices=True)

    def _traverse_device(self, ta) -> jax.Array:
        return self.traverse_bins(ta, self.bins)

    def traverse_bins(self, ta, bins) -> jax.Array:
        """Leaf index for every row (incl. out-of-bag) via bin-space
        traversal of the freshly built tree (handles the OOB score path
        of GBDT::UpdateScore and validation-set score updates)."""
        n = bins.shape[0]
        node = jnp.where(ta["n_leaves"] > 1, 0, -1) * jnp.ones(n, jnp.int32)
        miss_tbl = self.feature_miss_bin
        efb = self._efb_dev

        def gather_bin(f):
            if efb is None:
                return jnp.take_along_axis(
                    bins, f[:, None], axis=1)[:, 0].astype(jnp.int32)
            group_of, offset_of, nslots_of, skip_of = efb
            codes = jnp.take_along_axis(
                bins, group_of[f][:, None], axis=1)[:, 0].astype(jnp.int32)
            rel = codes - offset_of[f]
            inband = (rel >= 0) & (rel < nslots_of[f])
            dec = rel + (rel >= skip_of[f])
            return jnp.where(inband, dec, skip_of[f]).astype(jnp.int32)

        def cond(node):
            return jnp.any(node >= 0)

        def body(node):
            nid = jnp.maximum(node, 0)
            f = ta["split_feature"][nid]
            b = gather_bin(f)
            thr = ta["threshold_bin"][nid]
            mb = miss_tbl[f]
            go_left = b <= thr
            is_missing = (b == mb) & (mb >= 0)
            go_left = jnp.where(is_missing, ta["default_left"][nid], go_left)
            nxt = jnp.where(go_left, ta["left_child"][nid],
                            ta["right_child"][nid])
            return jnp.where(node < 0, node, nxt)

        node = jax.lax.while_loop(cond, body, node)
        return -node - 1

    # ------------------------------------------------------------------
    def feature_mask_tree(self) -> jax.Array:
        f = self.num_features
        mask = np.ones(f, dtype=bool)
        frac = self.config.feature_fraction
        if frac < 1.0:
            k = max(1, int(np.ceil(frac * f)))
            chosen = self._col_rng.choice(f, size=k, replace=False)
            mask[:] = False
            mask[chosen] = True
        return jnp.asarray(mask)

    def grow_device(self, grad, hess, perm, bag_cnt,
                    compute_score_update=True):
        """Returns (tree_arrays dict of device arrays, leaf_of_row)."""
        return self._grow_jit(self.code_bytes_dev(), grad, hess, perm,
                              jnp.int32(bag_cnt), self.feature_mask_tree(),
                              compute_score_update=compute_score_update)

    @functools.partial(jax.jit, static_argnums=0)
    def _valid_traverse_jit(self, ta, bins):
        return self.traverse_bins(ta, bins)

    def materialize_tree(self, tree_arrays: Dict) -> Tree:
        """Device tree arrays → host Tree (real feature ids, real
        thresholds, decision_type bits). One synchronous fetch."""
        ta = {k: np.asarray(v) for k, v in tree_arrays.items()}
        k = int(ta["n_leaves"])
        tree = Tree(self.num_leaves)
        tree.num_leaves = k
        ni = max(k - 1, 0)
        mappers = self.dataset.bin_mappers
        real_idx = self.dataset.real_feature_index
        inner_feat = ta["split_feature"][:ni]
        tree.split_feature_inner[:ni] = inner_feat
        tree.split_feature[:ni] = [real_idx[f] for f in inner_feat]
        tree.threshold_in_bin[:ni] = ta["threshold_bin"][:ni]
        tree.threshold[:ni] = [mappers[f].bin_to_value(int(tb))
                               for f, tb in zip(inner_feat,
                                                ta["threshold_bin"][:ni])]
        dt = np.zeros(max(ni, 1), dtype=np.int8)
        for i, f in enumerate(inner_feat):
            v = (2 if ta["default_left"][i] else 0) | \
                ((mappers[f].missing_type & 3) << 2)
            dt[i] = v
        tree.decision_type[:ni] = dt[:ni]
        tree.left_child[:ni] = ta["left_child"][:ni]
        tree.right_child[:ni] = ta["right_child"][:ni]
        tree.split_gain[:ni] = ta["split_gain"][:ni]
        tree.internal_value[:ni] = ta["internal_value"][:ni]
        tree.internal_weight[:ni] = ta["internal_weight"][:ni]
        tree.internal_count[:ni] = ta["internal_count"][:ni]
        tree.leaf_value[:k] = ta["leaf_value"][:k]
        tree.leaf_weight[:k] = ta["leaf_weight"][:k]
        tree.leaf_count[:k] = ta["leaf_count"][:k]
        tree.leaf_depth[:k] = ta["leaf_depth"][:k]
        return tree


class PendingTree:
    """Lazily-materialized device tree: keeps the raw device arrays until
    a host consumer needs a real Tree, so the training loop never blocks
    on a device→host fetch. Any Tree attribute access (num_leaves,
    to_string, leaf_index_raw, ...) transparently materializes the host
    Tree once and delegates to it, so consumers that read GBDT.models
    directly keep working without an explicit materialize pass."""

    def __init__(self, grower: FusedSerialGrower, tree_arrays: Dict) -> None:
        self._tree: Optional[Tree] = None
        self.grower = grower
        self.tree_arrays = tree_arrays
        self.pending_shrinkage = 1.0
        self.pending_bias = 0.0

    def apply_shrinkage(self, rate: float) -> None:
        if self._tree is not None:
            self._tree.apply_shrinkage(rate)
        else:
            self.pending_shrinkage *= rate

    def add_bias(self, val: float) -> None:
        if self._tree is not None:
            self._tree.add_bias(val)
        else:
            self.pending_bias += val

    def leaf_values_device(self):
        if self._tree is not None:
            return self._tree.leaf_values_device()
        return (self.tree_arrays["leaf_value"] * self.pending_shrinkage
                + self.pending_bias)

    def materialize(self) -> Tree:
        if self._tree is None:
            tree = self.grower.materialize_tree(self.tree_arrays)
            if self.pending_shrinkage != 1.0:
                tree.apply_shrinkage(self.pending_shrinkage)
            if self.pending_bias != 0.0:
                tree.add_bias(self.pending_bias)
            self._tree = tree
        return self._tree

    def __getattr__(self, name: str):
        # only reached when normal lookup fails → a Tree attribute;
        # materialize once and delegate. Guard against recursion during
        # unpickling/copy before __init__ has run.
        if name.startswith("__") or name in ("_tree", "grower", "tree_arrays",
                                             "pending_shrinkage",
                                             "pending_bias"):
            raise AttributeError(name)
        return getattr(self.materialize(), name)
