"""Fully on-device leaf-wise tree growth — one dispatch per iteration.

This is the TPU-critical redesign of the training hot path. The
reference's per-split control flow (serial_tree_learner.cpp:152-202)
costs it nothing on CPU, and its GPU learner tolerates a PCIe sync per
leaf (gpu_tree_learner.cpp). Here every host→device round trip costs
~100 ms over the accelerator tunnel, so num_leaves-1 split steps per
tree MUST run inside one compiled program:

- The whole split loop is a `lax.while_loop`; per-leaf state (ranges,
  sums, outputs, best-split records, the histogram pool) lives in
  fixed-size [num_leaves] device arrays — the HistogramPool
  (feature_histogram.hpp:1061) becomes a dense [L, F, B, 2] pool.
- Training rows live in the PLANAR [P, R] int32 layout of ops/plane.py
  (bin-code byte planes + grad/hess/label/score/row-id planes,
  lane-major). DataPartition::Split (data_partition.hpp:72) is the
  Pallas carry-stream kernel: in-register block compaction + aligned
  DMA writes — no per-row gather/scatter/sort anywhere in the loop,
  which removed the ~37-140 ns/row access tolls that dominated every
  row-major formulation (docs/PERF_NOTES.md).
- Leaf histograms use `lax.switch` over capacity buckets; the smaller
  child is histogrammed at its own bucket, the larger child is
  histogram subtraction, as in the reference (:396-404).
- In the persistent mode (no bagging, pointwise objective, one tree
  per iteration) the score/label/row-id ride inside the planar state
  ACROSS iterations in leaf-permuted order: gradients, tree growth,
  and the score update all happen in one program with zero [N]-sized
  scatters; scores are scattered back to row order only when a host
  consumer asks (GBDT.get_training_score).

Coverage: numerical AND categorical features (one-vs-rest + sorted
many-vs-many with the left-set bitset materialized on device and
routed through the partition kernel's prefetched scalars), serial and
sharded-data-parallel learners, any objective without leaf renewal,
bagging via a host-provided permutation, per-tree feature_fraction,
max_depth, basic monotone constraints, L1/L2/max_delta_step/path
smoothing, forced splits (BFS phase before the best-first loop) and
feature_fraction_bynode (per-scan-event masks). Interaction
constraints, extra_trees, CEGB and renew-tree-output objectives fall
back to the host-loop grower (treelearner/serial.py) — every rejection
is named by fused_reject_reason and warned about loudly.
"""
from __future__ import annotations

import contextlib
import os
from typing import Dict, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config
from ..io.dataset import BinnedDataset
from ..io.binning import BIN_CATEGORICAL
from ..models.tree import Tree
from ..ops import histogram as H
from ..ops import plane
from ..ops import quantize as Q
from ..ops import split as S
from ..utils import log

NEG_INF = jnp.float32(-jnp.inf)


def bag_active(config: Config) -> bool:
    """Whether row sampling re-permutes rows away from score order —
    shared by fused_reject_reason and the grower's
    _score_from_partition so the two can never disagree (a renew
    objective accepted here but non-persistent there would silently
    skip its leaf refit)."""
    return ((config.bagging_freq > 0
             and (config.bagging_fraction < 1.0
                  or config.pos_bagging_fraction < 1.0
                  or config.neg_bagging_fraction < 1.0))
            or config.boosting in ("goss", "rf"))


def fused_reject_reason(config: Config, dataset: BinnedDataset,
                        objective) -> Optional[str]:
    """Why a config cannot run the fused single-dispatch path (None =
    eligible). Every remaining rejection names the responsible option so
    the driver can warn LOUDLY about the ~10x host-loop perf cliff."""
    if not config.tpu_fused:
        return "tpu_fused=false"
    if config.tree_learner != "serial":
        return f"tree_learner={config.tree_learner}"
    if max((m.num_bin for m in dataset.bin_mappers
            if m.bin_type == BIN_CATEGORICAL), default=0) > 256:
        # categorical routing carries an 8-word (256-bin) bitset through
        # the partition kernel's prefetched scalars
        return "a categorical feature with > 256 bins (max_bin)"
    if config.forcedsplits_filename:
        # the forced phase reads parent histograms from the pool
        pool_mb = config.histogram_pool_size
        need = (max(config.num_leaves, 2) * dataset.num_features
                * max((m.num_bin for m in dataset.bin_mappers), default=2)
                * 2 * 4)
        if not (pool_mb <= 0 or need <= pool_mb * 1024 * 1024):
            return ("forcedsplits_filename with a histogram_pool_size "
                    "too small for the dense pool")
    if config.interaction_constraints:
        return "interaction_constraints"
    if config.extra_trees:
        return "extra_trees"
    if (config.cegb_tradeoff != 1.0 or config.cegb_penalty_split > 0
            or config.cegb_penalty_feature_coupled
            or config.cegb_penalty_feature_lazy):
        return "cegb_* (cost-effective gradient boosting)"
    if config.monotone_constraints and (
            config.monotone_constraints_method != "basic"
            or config.monotone_penalty > 0):
        # intermediate mode re-searches arbitrary leaves after a split —
        # host-loop territory (treelearner/monotone.py)
        return ("monotone_constraints_method=intermediate or "
                "monotone_penalty > 0")
    if config.use_quantized_grad:
        # the quantized pass rounds persistent_grads in-program and
        # renews leaf values from the raw f32 score/label planes — both
        # live only on the persistent path. Per-tree fused configs
        # (bagging/GOSS/RF/DART, multi-class) take the host-loop serial
        # learner, which quantizes per tree on its own.
        persist = (objective is not None
                   and getattr(objective, "persistent_aux", None) is not None
                   and objective.persistent_aux() is not None
                   and objective.num_tree_per_iteration == 1)
        if not persist or config.boosting != "gbdt" or bag_active(config):
            return ("use_quantized_grad outside the persistent path "
                    "(bagging/GOSS/RF/DART or a non-pointwise objective)")
    if objective is not None and objective.is_renew_tree_output:
        # the leaf refit runs in-program via _renew_leaf_outputs, which
        # needs the persistent path's label/score planes — reject
        # configs that would take the per-tree fused path instead
        # (bagging/GOSS/RF/DART re-permute rows away from score order)
        if (objective.persistent_renew_spec() is None
                or config.boosting != "gbdt" or bag_active(config)):
            return (f"objective={objective.name} (renew-tree-output leaf "
                    "refit outside the persistent path)")
    if dataset.num_features == 0:
        return "dataset has no usable features"
    return None


def fused_supported(config: Config, dataset: BinnedDataset,
                    objective) -> bool:
    """Static eligibility check for the fused path."""
    return fused_reject_reason(config, dataset, objective) is None


class FusedTreeState(NamedTuple):
    """Loop-carried device state; [L] = num_leaves slots."""
    data: jax.Array            # [P, R] planar training rows
    n_leaves: jax.Array        # scalar i32
    leaf_start: jax.Array      # [L] shard-local window starts
    leaf_count: jax.Array      # [L] shard-local window lengths
    leaf_count_g: jax.Array    # [L] GLOBAL row counts (== local 1-chip)
    leaf_sum_g: jax.Array      # [L]
    leaf_sum_h: jax.Array      # [L]
    leaf_output: jax.Array     # [L]
    leaf_depth: jax.Array      # [L]
    leaf_parent: jax.Array     # [L]
    leaf_cmin: jax.Array       # [L] monotone lower bound
    leaf_cmax: jax.Array       # [L]
    # per-leaf best split record
    best_gain: jax.Array       # [L] (-inf = unsplittable)
    best_feature: jax.Array    # [L]
    best_thr: jax.Array        # [L]
    best_dl: jax.Array         # [L] bool
    best_lg: jax.Array         # [L]
    best_lh: jax.Array         # [L]
    best_lcnt: jax.Array       # [L]
    best_lout: jax.Array       # [L]
    best_rg: jax.Array         # [L]
    best_rh: jax.Array         # [L]
    best_rcnt: jax.Array       # [L]
    best_rout: jax.Array       # [L]
    best_cat: jax.Array        # [L] bool — categorical split
    best_bits: jax.Array       # [L, 8] left-category bin bitset
    hist_pool: jax.Array       # [L, F, B, 2]
    # tree under construction (internal nodes [L-1])
    t_feature: jax.Array
    t_thr: jax.Array
    t_dl: jax.Array
    t_left: jax.Array
    t_right: jax.Array
    t_gain: jax.Array
    t_ivalue: jax.Array
    t_iweight: jax.Array
    t_icount: jax.Array
    t_cat: jax.Array           # [L-1] bool
    t_bits: jax.Array          # [L-1, 8]


class FusedSerialGrower:
    """Builds and owns the single-dispatch training-iteration program."""

    is_multichip = False

    @property
    def bins(self):
        if self._bins_dev is None:
            self._bins_dev = self.dataset.device_bins()
        return self._bins_dev

    def __init__(self, dataset: BinnedDataset, config: Config,
                 objective=None, num_rows_override=None,
                 num_rows_bucket=None) -> None:
        self.dataset = dataset
        self._num_rows_override = num_rows_override
        self.config = config
        self.objective = objective
        # HBM budgeting at wide-EFB scale: the row-major bin matrix is
        # only needed by the traverse paths (OOB scores, valid sets,
        # the bagging repack) — upload it LAZILY so the persistent path
        # does not hold [N, G] u8 in HBM next to the planar state
        # (13.2M x 500 groups = 6.6 GB that the training loop never
        # reads)
        self._bins_dev = None
        self.num_features = dataset.num_features
        mappers = dataset.bin_mappers
        self.max_num_bin = max((m.num_bin for m in mappers), default=2)
        self.num_leaves = max(config.num_leaves, 2)
        monotone = [dataset.monotone_constraint(i)
                    for i in range(self.num_features)]
        self.use_monotone = any(m != 0 for m in monotone)
        self.any_categorical = any(m.bin_type == BIN_CATEGORICAL
                                   for m in mappers)
        penalty = list(config.feature_contri) + \
            [1.0] * (self.num_features - len(config.feature_contri))
        self.meta = S.FeatureMeta.build(
            num_bin=[m.num_bin for m in mappers],
            missing_type=[m.missing_type for m in mappers],
            default_bin=[m.default_bin for m in mappers],
            is_categorical=[m.bin_type == BIN_CATEGORICAL for m in mappers],
            monotone=monotone,
            penalty=[float(p) for p in penalty[:self.num_features]])
        self.split_cfg = S.SplitConfig(
            lambda_l1=config.lambda_l1, lambda_l2=config.lambda_l2,
            min_data_in_leaf=config.min_data_in_leaf,
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            max_delta_step=config.max_delta_step,
            path_smooth=config.path_smooth,
            use_monotone=self.use_monotone,
            max_cat_threshold=config.max_cat_threshold,
            cat_l2=config.cat_l2, cat_smooth=config.cat_smooth,
            max_cat_to_onehot=config.max_cat_to_onehot,
            min_data_per_group=config.min_data_per_group)
        self.feature_miss_bin = jnp.asarray([
            (m.num_bin - 1 if m.missing_type == 2 else
             (m.default_bin if m.missing_type == 1 else -1))
            for m in mappers], dtype=jnp.int32)
        # EFB bundle views (None on dense/trivial datasets)
        self._efb_dev = dataset.device_bundle_tables()
        self._efb_hist = dataset.device_hist_tables()
        self._tables_cache = None
        self.group_max_bin = dataset.group_max_bins
        # backend dispatch: ops/histogram.hist_method is the ONE shared
        # precision/layout choice for every learner; partition follows
        # suit (LGBM_TPU_PART selects the carry-stream kernel
        # generation). The dataset argument lets the occupancy-driven
        # dispatcher pick the row-wise multival layout for wide-sparse
        # shapes (ops/multival.py).
        self._hist_method = H.hist_method(config, dataset)
        self._part_method = (os.environ.get("LGBM_TPU_PART", "pallas2")
                             if self._hist_method is not None else "ref")
        # quantized-gradient training (ops/quantize.py): the persistent
        # iteration quantizes grads in-program, the grad plane carries
        # PACKED (qg << 16 | qh) words bitcast through the f32 lanes,
        # and the hist pool holds exact int32 level-sums. Host-side
        # per-iteration counter drives the stochastic-rounding keys.
        self._quant = bool(config.use_quantized_grad)
        self._quant_iter = 0
        self._quant_base_key = (
            jax.random.PRNGKey(config.objective_seed ^ 0x51A7)
            if self._quant else None)

        # planar layout: label/score/weight planes only when the
        # objective can run the persistent in-program loop. Codes pack
        # at 4 bits when every (bundle) column fits 16 bins — the
        # reference's DenseBin IS_4BIT mode (dense_bin.hpp:17-21),
        # halving code-plane HBM footprint and partition bandwidth.
        self._num_cols = int(dataset.bins.shape[1])
        group_bins = (dataset.group_max_bins
                      if dataset.device_hist_tables() is not None
                      else self.max_num_bin)
        if group_bins <= 16:
            self._code_bits = 4
        else:
            self._code_bits = 8 * int(
                np.dtype(dataset.bins.dtype).itemsize)
        n_actual = (dataset.num_data if num_rows_override is None
                    else num_rows_override)
        # canonical row bucketing (compile/signature.py): the layout is
        # sized to the bucket so every row-shaped executable is shared
        # across same-bucket datasets; the real row count rides through
        # the programs as the traced n_valid / bag-count argument and
        # pad lanes stay outside every window
        n = n_actual if num_rows_bucket is None \
            else max(int(num_rows_bucket), n_actual)
        self.actual_rows = n_actual
        persist = (objective is not None
                   and getattr(objective, "persistent_aux", None) is not None
                   and objective.persistent_aux() is not None
                   and objective.num_tree_per_iteration == 1)
        has_w = persist and objective.persistent_aux()[1] is not None

        # row-wise multival layout (ops/multival.py): the dataset's
        # present (group, bin) codes are packed once into [K, N] slot
        # planes that ride the planar state (make_layout mv_planes), so
        # the partition kernels keep them row-aligned for free and the
        # histogram pass reads K*4 bytes/row instead of G code bytes
        self._mv_layout = None
        self._mv_total_bins = 0
        self._mv_dev = None
        self._mv_tables = None
        mv_planes = 0
        if self._hist_method == "multival_pallas":
            from ..ops import multival as MV
            occ = dataset.occupancy
            if dataset.bundles is not None:
                gnb = dataset.bundles.group_num_bins
            else:
                gnb = np.asarray([m.num_bin for m in mappers], np.int32)
            mv_codes, mv_layout = MV.build_rowwise_codes(
                dataset.bins, gnb, occ.default_code)
            self._mv_layout = mv_layout
            self._mv_total_bins = mv_layout.total_bins
            self._mv_dev = jnp.asarray(np.ascontiguousarray(mv_codes.T))
            self._mv_tables = MV.group_tables(gnb, occ.default_code)
            mv_planes = mv_layout.row_capacity   # a multiple of 8

        def mk_layout(tile):
            return plane.make_layout(
                self._num_cols, self._code_bits, n,
                with_label=persist, with_score=persist, with_weight=has_w,
                tile=tile, mv_planes=mv_planes)

        self.layout = mk_layout(plane.DEF_TILE)
        # scoped-VMEM budgeting: every partition staging buffer spans
        # the full plane count P, so wide-EFB states (hundreds of code
        # planes) overflow the 16 MB scoped VMEM at the default tile —
        # shrink the lane tile until even the v1 kernel fits
        while (self.layout.tile > 512
               and plane.partition_vmem_bytes(self.layout, "pallas")
               > plane.PART_VMEM_BUDGET):
            t = self.layout.tile // 2
            log.info("partition VMEM at P=%d exceeds budget: shrinking "
                     "lane tile to %d", self.layout.num_planes, t)
            self.layout = mk_layout(t)
        self.persistent_capable = persist
        self._codes_planes_dev = None   # built lazily
        # wide-EFB HBM budgeting: the v2 partition kernel's scratch is
        # TWO window regions (L and R streams); when the planar state
        # itself is multi-GB, v1's single-region scratch keeps
        # state+scratch at 2x instead of 3x (the Allstate shape:
        # ~60 code planes x 13.2M lanes). v2 also holds 3x the staging
        # VMEM, so wide-plane states take v1 for the scoped limit too.
        if self._part_method == "pallas2":
            state_gb = (self.layout.num_planes * self.layout.num_lanes
                        * 4 / 1e9)
            v2_vmem = plane.partition_vmem_bytes(self.layout, "pallas2")
            if state_gb > 2.5 or v2_vmem > plane.PART_VMEM_BUDGET:
                self._part_method = "pallas"
                log.info("planar state %.1f GB / v2 scratch %.1f MB: "
                         "selecting the single-scratch partition kernel",
                         state_gb, v2_vmem / 1e6)

        # histogram_pool_size (MB; <=0 unlimited — reference
        # feature_histogram.hpp:1061 HistogramPool): when the dense
        # [L, F, B, 2] pool would not fit, run pool-less — both
        # children's histograms are computed directly (no subtraction),
        # nothing is cached, memory is O(F*B) instead of O(L*F*B)
        pool_mb = config.histogram_pool_size
        need = (self.num_leaves * self.num_features
                * self.max_num_bin * 2 * 4)
        self._use_hist_pool = pool_mb <= 0 or need <= pool_mb * 1024 * 1024
        if not self._use_hist_pool:
            log.info("histogram pool (%.0f MB) exceeds histogram_pool_size"
                     "=%.0f MB: disabling histogram subtraction",
                     need / 1e6, pool_mb)

        # user-forced splits: BFS schedule precomputed host-side
        # (leaf slot / inner feature / threshold bin per forced split);
        # the slot ids replay exactly the fused state's deterministic
        # slot assignment (split leaf keeps its slot, right child takes
        # slot n_leaves). Reference: ForceSplits,
        # serial_tree_learner.cpp:427
        self._forced_sched = None
        self._forced_sig = None
        if config.forcedsplits_filename:
            from .serial import _load_forced_splits
            forced = _load_forced_splits(config.forcedsplits_filename)
            sched = []
            if forced is not None:
                queue = [(forced, 0)]
                nl = 1
                while queue and nl < self.num_leaves:
                    node, slot = queue.pop(0)
                    rf = node.get("feature")
                    if rf is None:
                        continue
                    inner = dataset.inner_feature_index.get(int(rf))
                    if inner is None:
                        log.warning("Forced split on unused feature %s "
                                    "ignored", rf)
                        continue
                    m = mappers[inner]
                    tb = int(m.value_to_bin(float(node["threshold"])))
                    tb = max(0, min(tb, m.num_bin - 2))
                    sched.append((slot, inner, tb))
                    right_slot = nl
                    nl += 1
                    if isinstance(node.get("left"), dict):
                        queue.append((node["left"], slot))
                    if isinstance(node.get("right"), dict):
                        queue.append((node["right"], right_slot))
            if sched:
                arr = np.asarray(sched, np.int32)
                self._forced_sched = (jnp.asarray(arr[:, 0]),
                                      jnp.asarray(arr[:, 1]),
                                      jnp.asarray(arr[:, 2]))
                # forced splits are closed-over device constants: their
                # host values must refine the compile signature
                self._forced_sig = arr.tolist()

        # score updates can reuse the partition's leaf assignment only
        # when every scored row is in-bag (no bagging/GOSS/RF); with
        # bagging the out-of-bag rows are never partitioned and the
        # fallback is the tree re-traversal
        self._score_from_partition = not bag_active(config)

        # multi-chip: name of the mesh axis to psum histograms/counts
        # over (set by the data-parallel wrapper; None on one chip)
        self.psum_axis = None
        self._col_rng = np.random.RandomState(config.feature_fraction_seed)
        # capacity ladder for the REF-path lax.switch branches (the
        # XLA-sliced partition/histogram fallbacks need a static window
        # width). The pallas paths no longer ladder: their block sweeps
        # ride a dynamic grid dimension (ops/plane.py / ops/histogram.py
        # cap=None), so ONE lowered kernel serves every leaf size, the
        # while-body HLO holds one copy of each kernel instead of
        # LGBM_TPU_LADDER x len(caps), and no step is ever launched past
        # the leaf window (the dynamic sweep subsumes the old
        # skipped-step cost model). Tile / row-block lengths are fixed
        # at the top-capacity choice — per-step overhead (~4 us) still
        # amortizes, small leaves just read one partially-valid block.
        factor = int(np.clip(
            int(os.environ.get("LGBM_TPU_LADDER", 4)), 2, 64))
        tile = self.layout.tile
        top = self.layout.num_lanes - self.layout.max_tile
        from ..ops.partition import capacity_ladder
        self._caps = capacity_ladder(top, tile * 4, factor)
        self._dyn_tile = self._branch_tile(top)
        self._dyn_hist_rb = self._branch_hist_rb(top)
        from ..obs import instrument_kernel
        # jit entry points go through the AOT compile manager
        # (lightgbm_tpu/compile): same-signature growers share one
        # executable, executables persist on disk, and warmup threads
        # can compile them ahead of the first iteration. The sharded
        # per-shard growers (num_rows_override set) keep plain jit —
        # their programs mutate post-init (psum_axis) and run under
        # shard_map.
        self._mgr = None
        if num_rows_override is None:
            from ..compile import get_manager
            self._mgr = get_manager()
        if self._mgr is not None:
            sig = self._compile_signature()
            self._grow_entry = self._mgr.shared_entry(
                "fused/grow_tree", sig,
                lambda: jax.jit(
                    self._entry_grow_tree,
                    static_argnames=("compute_score_update",)))
            self._iter_entry = self._mgr.shared_entry(
                "fused/train_iter", sig,
                lambda: jax.jit(self._entry_train_iter, donate_argnums=1),
                donate_argnums=(1,))
            self._sync_entry = self._mgr.shared_entry(
                "fused/sync_scores", sig,
                lambda: jax.jit(self._sync_scores))
            self._trav_entry = self._mgr.shared_entry(
                "fused/traverse", sig,
                lambda: jax.jit(self._entry_traverse))
            self._grow_jit = instrument_kernel(
                self._grow_entry, "fused", name="fused/grow_tree")
            self._iter_jit = instrument_kernel(
                self._iter_entry, "fused", name="fused/train_iter")
            self._sync_jit = instrument_kernel(
                self._sync_entry, "fused", name="fused/sync_scores")
            self._trav_jit = self._trav_entry
            self._register_warmup_specs()
        else:
            self._grow_jit = instrument_kernel(
                jax.jit(self._entry_grow_tree,  # tpulint: jit-ok(manager-disabled fallback branch)
                        static_argnames=("compute_score_update",)),
                "fused", name="fused/grow_tree")
            self._iter_jit = instrument_kernel(
                jax.jit(self._entry_train_iter, donate_argnums=1),  # tpulint: jit-ok(manager-disabled fallback branch)
                "fused", name="fused/train_iter")
            self._sync_jit = instrument_kernel(
                jax.jit(self._sync_scores),  # tpulint: jit-ok(manager-disabled fallback branch)
                "fused",
                name="fused/sync_scores")
            self._trav_jit = jax.jit(self._entry_traverse)  # tpulint: jit-ok(manager-disabled fallback branch)

    # ------------------------------------------------------------------
    def codes_planes(self) -> jax.Array:
        if self._codes_planes_dev is None:
            if self._bins_dev is not None:
                self._codes_planes_dev = plane.build_codes_planes(
                    self._bins_dev, self.layout)
            elif self.dataset.bins.nbytes > (1 << 31):
                # chunked host->device packing: a one-shot row-major
                # upload at wide-EFB scale (13.2M x 581 = 7.7 GB u8)
                # OOMs HBM next to the planar state before the async
                # free lands
                self._codes_planes_dev = plane.build_codes_planes_chunked(
                    self.dataset.bins, self.layout)
            else:
                # transient row-major upload; the persistent path never
                # needs the row-major copy again
                self._codes_planes_dev = plane.build_codes_planes(
                    jnp.asarray(self.dataset.bins), self.layout)
        return self._codes_planes_dev

    # -- AOT compile manager integration -------------------------------
    def _tables(self) -> Dict:
        """Dataset-valued lookup tables as ONE pytree, passed as a jit
        ARGUMENT to every entry point. Closing over them instead would
        bake each dataset's bin boundaries into the executable, which
        kills cross-dataset executable sharing (and would silently alias
        programs if the compile signature missed a value).

        The snapshot is frozen on first use: `_bind_tables` temporarily
        rebinds the instance attributes to TRACERS while a warmup thread
        lowers an entry, and a concurrent training-thread call site must
        never pick those up as call arguments."""
        t = self._tables_cache
        if t is None:
            m = self.meta
            t = {
                "meta": {"num_bin": m.num_bin,
                         "missing_type": m.missing_type,
                         "default_bin": m.default_bin,
                         "is_categorical": m.is_categorical,
                         "monotone": m.monotone, "penalty": m.penalty},
                "miss": self.feature_miss_bin,
                "efb": self._efb_dev,
                "efb_hist": self._efb_hist,
                "mv": self._mv_tables,
            }
            # canonicalize scalar leaves (e.g. the EFB hist_tables' bg
            # int) to arrays so warmup specs can take avals of every
            # leaf and live calls produce the identical shape signature
            t = self._tables_cache = jax.tree_util.tree_map(
                lambda a: a if isinstance(a, jax.Array) else jnp.asarray(a),
                t)
        return t

    @contextlib.contextmanager
    def _bind_tables(self, tables: Dict):
        """Swap the instance's table attributes for traced values while
        an entry point traces. Serialized under the manager's trace lock
        (re-entrant) so a warmup thread lowering one entry can never
        race the training thread tracing another on this instance."""
        from ..compile import get_manager
        with get_manager()._trace_lock:
            saved = (self.meta, self.feature_miss_bin, self._efb_dev,
                     self._efb_hist, self._mv_tables)
            m = tables["meta"]
            self.meta = S.FeatureMeta(
                num_bin=m["num_bin"], missing_type=m["missing_type"],
                default_bin=m["default_bin"],
                is_categorical=m["is_categorical"],
                monotone=m["monotone"], penalty=m["penalty"],
                cat_idx=saved[0].cat_idx)
            self.feature_miss_bin = tables["miss"]
            self._efb_dev = tables["efb"]
            self._efb_hist = tables["efb_hist"]
            self._mv_tables = tables.get("mv")
            try:
                yield
            finally:
                (self.meta, self.feature_miss_bin, self._efb_dev,
                 self._efb_hist, self._mv_tables) = saved

    def _compile_signature(self) -> Dict:
        """Everything that shapes the traced programs EXCEPT the table
        values (traced args) and row-shaped arrays (in the per-call
        shape signature). Equal signatures => identical jaxprs."""
        from ..compile import config_signature
        return {
            "config": config_signature(self.config),
            "layout": tuple(self.layout),
            "caps": tuple(self._caps),
            "dyn": (self._dyn_tile, self._dyn_hist_rb),
            "num_features": self.num_features,
            "max_num_bin": self.max_num_bin,
            "group_max_bin": self.group_max_bin,
            "num_leaves": self.num_leaves,
            "any_categorical": self.any_categorical,
            "use_monotone": self.use_monotone,
            "cat_idx": tuple(self.meta.cat_idx),
            "hist_method": self._hist_method,
            "mv_total_bins": self._mv_total_bins,
            "part_method": self._part_method,
            "use_hist_pool": self._use_hist_pool,
            "score_from_partition": self._score_from_partition,
            "persistent": self.persistent_capable,
            "objective": (type(self.objective).__name__
                          if self.objective is not None else None),
            "split_cfg": self.split_cfg,
            "forced": self._forced_sig,
            "efb": self._efb_dev is not None,
            "efb_hist": self._efb_hist is not None,
        }

    def _entry_grow_tree(self, tables, codes_planes, grad, hess, perm,
                         bag_cnt, feature_mask, bins_rowmajor=None,
                         mv=None, compute_score_update: bool = True):
        with self._bind_tables(tables):
            return self._grow_tree(codes_planes, grad, hess, perm,
                                   bag_cnt, feature_mask, bins_rowmajor,
                                   mv, compute_score_update)

    def _entry_train_iter(self, tables, data, feature_mask, shrinkage,
                          bias, n_valid, key=None):
        with self._bind_tables(tables):
            return self._train_iter(data, feature_mask, shrinkage, bias,
                                    n_valid=n_valid, key=key)

    def _entry_traverse(self, tables, ta, bins):
        with self._bind_tables(tables):
            return self.traverse_bins(ta, bins)

    def _register_warmup_specs(self) -> None:
        """Abstract call specs (ShapeDtypeStructs) for the entries the
        training loop will hit, so compile/warmup.py can compile them
        before (or concurrently with) the first iteration."""
        Ly = self.layout
        aval = jax.ShapeDtypeStruct
        t_avals = jax.tree_util.tree_map(
            lambda a: aval(a.shape, a.dtype), self._tables())
        data_aval = aval((Ly.num_planes, Ly.num_lanes), jnp.int32)
        if self.config.feature_fraction_bynode < 1.0:
            mask_aval = aval((2 * self.num_leaves, self.num_features),
                             jnp.bool_)
        else:
            mask_aval = aval((self.num_features,), jnp.bool_)
        f32s = aval((), jnp.float32)
        i32s = aval((), jnp.int32)
        if self.persistent_capable and self._score_from_partition:
            if self._quant:
                key_aval = aval((2,), jnp.uint32)
                self._iter_entry.add_spec(
                    (t_avals, data_aval, mask_aval, f32s, f32s, i32s,
                     key_aval))
            else:
                self._iter_entry.add_spec(
                    (t_avals, data_aval, mask_aval, f32s, f32s, i32s))
            self._sync_entry.add_spec((data_aval,))
        elif self._score_from_partition:
            n = self.actual_rows
            cp_aval = aval((Ly.code_planes, Ly.num_lanes), jnp.int32)
            fvec = aval((n,), jnp.float32)
            perm_aval = aval((Ly.num_rows,), jnp.int32)
            mv_aval = (aval(self._mv_dev.shape, jnp.int32)
                       if self._mv_dev is not None else None)
            self._grow_entry.add_spec(
                (t_avals, cp_aval, fvec, fvec, perm_aval, i32s, mask_aval,
                 None, mv_aval), {"compute_score_update": True})

    def _branch_tile(self, cap: int) -> int:
        """Per-branch partition processing tile: the kernels are
        per-STEP-overhead bound (~4 us/step, scripts/part_micro.py), so
        larger capacity branches use larger tiles — up to cap/8, the
        layout's padded max_tile, and the scoped-VMEM budget."""
        Ly = self.layout
        s = Ly.tile
        while (s * 2 <= Ly.max_tile and s * 2 * 8 <= cap
               and cap % (s * 2) == 0       # window geometry requires it
               and plane.partition_vmem_bytes_at(
                   Ly.num_planes, s * 2, self._part_method)
               <= plane.PART_VMEM_BUDGET):
            s *= 2
        return s

    def _branch_hist_rb(self, cap: int) -> int:
        """Per-branch histogram row-block length (same per-step
        amortization as _branch_tile; the planar hist kernel's VMEM
        footprint is small, so only cap/8 and max_tile bound it)."""
        rb = min(H.PLANAR_RB, self.layout.max_tile)
        while rb > 1024 and cap % rb:
            rb //= 2                         # window coverage requires it
        while (rb * 2 <= min(8192, self.layout.max_tile, cap // 8)
               and cap % (rb * 2) == 0):
            rb *= 2
        return rb

    def _switch_by_cap(self, count, branches_of_cap, *args):
        """Static-capacity ladder dispatch — REF/row-major paths only
        (XLA slices need compile-time widths). The pallas kernel paths
        use the dynamic-grid cap=None mode instead and never ladder."""
        branches = [branches_of_cap(c) for c in self._caps]
        cap_arr = jnp.asarray(self._caps, jnp.int32)
        idx = jnp.searchsorted(cap_arr, jnp.maximum(count, 1))
        idx = jnp.minimum(idx, len(self._caps) - 1)
        return jax.lax.switch(idx, branches, *args)  # tpulint: switch-ok(XLA-sliced ref fallback needs static window widths; pallas paths are ladder-free)

    def _psum(self, x):
        """Cross-shard sum (reference Network::Allreduce of histogram
        buffers, data_parallel_tree_learner.cpp:169) — identity on one
        chip."""
        if self.psum_axis is None:
            return x
        return jax.lax.psum(x, self.psum_axis)

    def _psum_max(self, x):
        """Cross-shard max — identity on one chip (the quantization
        scales must agree across shards before any int32 hist psum)."""
        if self.psum_axis is None:
            return x
        return jax.lax.pmax(x, self.psum_axis)

    def _window_hist(self, b, g, h):
        """Histogram of bin codes with masked weights; EFB bundle
        columns are gathered back to per-feature space (FixHistogram
        mfb reconstruction)."""
        nbins = (self.group_max_bin if self._efb_hist is not None
                 else self.max_num_bin)
        return self._hist_from_groups(
            H.histogram(b, g, h, nbins, method=self._hist_method))

    def _hist_from_groups(self, ghist):
        """Group-level [G, Bg, 2] -> per-feature [F, B, 2] (EFB
        FixHistogram mfb reconstruction) or identity when unbundled."""
        if self._efb_hist is None:
            return ghist
        from ..io.efb import per_feature_hist
        total = ghist[0].sum(axis=0)
        return per_feature_hist(ghist, self._efb_hist, total[0], total[1])

    def _leaf_hist_switch(self, data, start, count):
        """Histogram of a leaf range straight off the planar state; the
        CPU/oracle path goes through the row-major bridge instead.

        The planar pallas kernel takes the dynamic-grid mode (cap=None):
        one lowered program for every leaf size, no capacity switch. The
        row-major bridge keeps the static-capacity ladder — its window
        slice width is a compile-time constant by construction."""
        Ly = self.layout
        R = Ly.num_lanes
        nbins = (self.group_max_bin if self._efb_hist is not None
                 else self.max_num_bin)
        # planar kernel reads CS super-chunks of SP planes off the grid;
        # ensure the padded super-chunks never read past the plane count
        _, sp, _, cs = H.planar_grid_dims(nbins, Ly.code_bits, Ly.num_cols)
        planar_ok = (self._hist_method is not None
                     and cs * sp <= Ly.num_planes)
        dtype = (jnp.bfloat16 if self._hist_method == "radix_pallas_bf16"
                 else jnp.float32)

        if self._hist_method == "multival_pallas":
            return self._leaf_hist_multival(data, start, count)

        if planar_ok:
            ghist = H.histogram_planar_pallas(
                data, start, count, num_bins=nbins,
                num_cols=Ly.num_cols, code_bits=Ly.code_bits,
                grad_plane=Ly.grad, cap=None, dtype=dtype,
                rows_per_block=self._dyn_hist_rb, quant=self._quant)
            return self._hist_from_groups(ghist)

        def branch(cap):
            def fn(data, start, count):
                rs = jnp.clip(jnp.asarray(start, jnp.int32), 0, R - cap)
                codes, gh = plane.window_rowmajor(data, self.layout, rs,
                                                  cap=cap)
                off = jnp.asarray(start, jnp.int32) - rs
                pos = jnp.arange(cap, dtype=jnp.int32)
                valid = (pos >= off) & (pos < off + count)
                if self._quant:
                    # the grad plane carries packed (qg, qh) words
                    # bitcast through the f32 lanes — unpack to int32
                    # levels so the hist kernels take their exact
                    # integer-accumulation paths
                    qg, qh = Q.unpack_gh(plane.f32_as_i32(gh[:, 0]))
                    zero = jnp.zeros((), jnp.int32)
                    g = jnp.where(valid, qg, zero)
                    h = jnp.where(valid, qh, zero)
                else:
                    g = jnp.where(valid, gh[:, 0], 0.0)
                    h = jnp.where(valid, gh[:, 1], 0.0)
                return self._window_hist(codes, g, h)
            return fn

        return self._switch_by_cap(count, branch, data, start, count)

    def _leaf_hist_multival(self, data, start, count, interpret=False):
        """Leaf histogram off the row-wise multi-value planes (wide-
        sparse shape): the kernel accumulates a flat [T+1, 2] pair
        vector over present codes only, then per-group rows are gathered
        back and the absent default cell of each group is reconstructed
        from the sentinel leaf totals (flat cell T)."""
        from ..ops import multival as MV
        Ly = self.layout
        dtype = (jnp.bfloat16
                 if self.config.tpu_hist_dtype == "bfloat16"
                 else jnp.float32)
        flat = MV.histogram_multival_planar(
            data, start, count,
            mv_start=Ly.mv_start, mv_planes=Ly.mv_planes,
            total_bins=self._mv_total_bins, grad_plane=Ly.grad,
            dtype=dtype, rows_per_block=self._dyn_hist_rb,
            quant=self._quant, interpret=interpret)
        ghist = MV.group_hist_from_flat(flat, self._mv_tables)
        if self._efb_hist is None:
            return ghist
        from ..io.efb import per_feature_hist
        total = flat[-1]
        return per_feature_hist(ghist, self._efb_hist, total[0], total[1])

    def _split_step(self, data, start, count, feature, thr, dl, miss_bin,
                    cat=None, bits=None):
        """Split one leaf: the carry-stream partition kernel moves its
        rows (ops/plane.py), then the smaller child's histogram comes
        from the freshly contiguous range at its own capacity bucket."""
        rscal = plane.route_scalars(self.layout, feature, thr, dl, miss_bin,
                                    self._efb_dev, is_cat=cat,
                                    cat_bitset=bits)

        if self._part_method in ("pallas", "pallas2"):
            # dynamic-grid partition: one lowered kernel for every leaf
            # size (ops/plane.py cap=None) — no capacity switch
            return plane.partition_window(
                data, self.layout, start, count, rscal, cap=None,
                method=self._part_method, tile=self._dyn_tile)

        def branch(cap):
            def fn(data, start, count, rscal):
                return plane.partition_window(
                    data, self.layout, start, count, rscal, cap=cap,
                    method=self._part_method, tile=self._branch_tile(cap))
            return fn

        data, nleft = self._switch_by_cap(count, branch, data, start, count,
                                          rscal)
        return data, nleft

    def _scan_leaf(self, hist, sum_g, sum_h, count, output, cmin, cmax,
                   feature_mask, qscales=None):
        """Best split of one leaf from its pooled histogram; categorical
        features go through the merged numerical+categorical scan and
        materialize their left-category bitset HERE (the device
        analogue of serial.py _cat_bins), so the loop state only
        carries [8] words per leaf, not the full sorted order.
        ``qscales``: (grad_scale, hess_scale) when the pool holds int32
        level-sums — the scans themselves always run in f32."""
        if qscales is not None:
            hist = S.dequantize_hist(hist, qscales[0], qscales[1])
        if self.any_categorical:
            res = S.best_split(hist, self.meta, self.split_cfg, sum_g,
                               sum_h, count, output, cmin, cmax,
                               any_categorical=True)
        else:
            res = S.numerical_split_scan(hist, self.meta, self.split_cfg,
                                         sum_g, sum_h, count, output,
                                         cmin, cmax)
        gains = jnp.where(feature_mask, res["gain"], S.K_MIN_SCORE)
        f = jnp.argmax(gains).astype(jnp.int32)
        g = gains[f]
        ok = jnp.isfinite(g) & (g > 0.0) \
            & (count >= 2 * self.split_cfg.min_data_in_leaf)
        out = dict(
            gain=jnp.where(ok, g, NEG_INF),
            feature=f,
            thr=res["threshold"][f],
            dl=res["default_left"][f],
            lg=res["left_sum_gradient"][f], lh=res["left_sum_hessian"][f],
            lcnt=res["left_count"][f], lout=res["left_output"][f],
            rg=res["right_sum_gradient"][f], rh=res["right_sum_hessian"][f],
            rcnt=res["right_count"][f], rout=res["right_output"][f])
        if self.any_categorical:
            out["cat"] = self.meta.is_categorical[f]
            out["bits"] = self._cat_bitset_device(res, f)
        else:
            out["cat"] = jnp.bool_(False)
            out["bits"] = jnp.zeros(8, jnp.int32)
        return out

    def _cat_bitset_device(self, res, f):
        """[8] i32 left-category bin bitset from the categorical scan's
        (family, position, sorted order, used) description — family 0 is
        the single one-vs-rest bin, 1/2 are prefix/suffix of the sorted
        order (feature_histogram.hpp:278 one-hot and directional scans;
        host-side mirror: serial.py _cat_bins)."""
        fam = res["cat_family"][f]
        pos = jnp.asarray(res["threshold"][f], jnp.int32)
        order = res["cat_sorted_order"][f].astype(jnp.int32)   # [B]
        used = res["cat_used_bin"][f]
        B = order.shape[0]
        idx = jnp.arange(B, dtype=jnp.int32)
        sel_fwd = idx <= pos
        sel_bwd = (idx >= used - 1 - pos) & (idx < used)
        sel = jnp.where(fam == 1, sel_fwd, sel_bwd) & (fam != 0)
        bins_eff = jnp.where(fam == 0, pos, order)
        sel = sel | ((fam == 0) & (idx == 0))
        bit = jnp.left_shift(jnp.int32(1), bins_eff & 31)
        words = []
        for w in range(8):
            words.append(jnp.sum(jnp.where(
                sel & ((bins_eff >> 5) == w), bit, 0)))
        return jnp.stack(words)

    def _scan_two_leaves(self, hist2, sum_g2, sum_h2, count2, output2,
                         cmin2, cmax2, feature_mask2, qscales=None):
        """Both children's best splits from one vmapped scan (halves the
        per-split scan kernel count vs two sequential _scan_leaf calls).
        feature_mask2: [2, F] — per-child masks (identical rows unless
        feature_fraction_bynode is active)."""
        res2 = jax.vmap(
            lambda h, sg, sh, c, o, lo, hi, m: self._scan_leaf(
                h, sg, sh, c, o, lo, hi, m, qscales=qscales)
        )(hist2, sum_g2, sum_h2, count2, output2, cmin2, cmax2,
          feature_mask2)
        first = {k: v[0] for k, v in res2.items()}
        second = {k: v[1] for k, v in res2.items()}
        return first, second

    # ------------------------------------------------------------------
    def _grow_tree_core(self, data, bag_cnt, feature_mask, qscales=None):
        """The while_loop tree builder over planar data. Returns
        (tree arrays dict, final FusedTreeState). feature_mask: [F]
        per-tree mask, or [2L, F] per-scan-event masks (see
        feature_masks_for_tree) — the rank is a static branch.
        ``qscales``: (grad_scale, hess_scale) traced scalars when the
        grad plane carries packed quantized levels; the hist pool and
        the subtraction then stay in exact int32, and every per-leaf
        f32 state field (sums, outputs) is dequantized at the scan
        boundary."""
        L = self.num_leaves
        F, B = self.num_features, self.max_num_bin
        f32, i32 = jnp.float32, jnp.int32
        quant = qscales is not None
        bynode = feature_mask.ndim == 2
        root_mask = feature_mask[0] if bynode else feature_mask

        root_hist = self._psum(self._leaf_hist_switch(data, jnp.int32(0),
                                                      bag_cnt))
        bag_cnt_g = self._psum(jnp.asarray(bag_cnt, i32))
        if quant:
            sum_g = jnp.sum(root_hist[0, :, 0]).astype(f32) * qscales[0]
            sum_h = jnp.sum(root_hist[0, :, 1]).astype(f32) * qscales[1]
        else:
            sum_g = jnp.sum(root_hist[0, :, 0])
            sum_h = jnp.sum(root_hist[0, :, 1])
        root_best = self._scan_leaf(root_hist, sum_g, sum_h, bag_cnt_g,
                                    f32(0.0), f32(-jnp.inf), f32(jnp.inf),
                                    root_mask, qscales=qscales)

        def arr(val, dtype=f32):
            return jnp.full((L,), val, dtype)

        st = FusedTreeState(
            data=data, n_leaves=i32(1),
            leaf_start=arr(0, i32).at[0].set(0),
            leaf_count=arr(0, i32).at[0].set(bag_cnt),
            leaf_count_g=arr(0, i32).at[0].set(bag_cnt_g),
            leaf_sum_g=arr(0.0).at[0].set(sum_g),
            leaf_sum_h=arr(0.0).at[0].set(sum_h),
            leaf_output=arr(0.0),
            leaf_depth=arr(0, i32),
            leaf_parent=arr(-1, i32),
            leaf_cmin=arr(-jnp.inf), leaf_cmax=arr(jnp.inf),
            best_gain=arr(NEG_INF).at[0].set(root_best["gain"]),
            best_feature=arr(0, i32).at[0].set(root_best["feature"]),
            best_thr=arr(0, i32).at[0].set(root_best["thr"]),
            best_dl=arr(False, bool).at[0].set(root_best["dl"]),
            best_lg=arr(0.0).at[0].set(root_best["lg"]),
            best_lh=arr(0.0).at[0].set(root_best["lh"]),
            best_lcnt=arr(0, i32).at[0].set(root_best["lcnt"]),
            best_lout=arr(0.0).at[0].set(root_best["lout"]),
            best_rg=arr(0.0).at[0].set(root_best["rg"]),
            best_rh=arr(0.0).at[0].set(root_best["rh"]),
            best_rcnt=arr(0, i32).at[0].set(root_best["rcnt"]),
            best_rout=arr(0.0).at[0].set(root_best["rout"]),
            best_cat=arr(False, bool).at[0].set(root_best["cat"]),
            best_bits=jnp.zeros((L, 8), i32).at[0].set(root_best["bits"]),
            hist_pool=(jnp.zeros((L, F, B, 2), i32 if quant else f32)
                       .at[0].set(root_hist)
                       if self._use_hist_pool
                       else jnp.zeros((1, 1, 1, 2), i32 if quant else f32)),
            t_feature=jnp.zeros((L - 1,), i32),
            t_thr=jnp.zeros((L - 1,), i32),
            t_dl=jnp.zeros((L - 1,), bool),
            t_left=jnp.zeros((L - 1,), i32),
            t_right=jnp.zeros((L - 1,), i32),
            t_gain=jnp.zeros((L - 1,), f32),
            t_ivalue=jnp.zeros((L - 1,), f32),
            t_iweight=jnp.zeros((L - 1,), f32),
            t_icount=jnp.zeros((L - 1,), i32),
            t_cat=jnp.zeros((L - 1,), bool),
            t_bits=jnp.zeros((L - 1, 8), i32),
        )

        max_depth = self.config.max_depth
        mono_dev = self.meta.monotone

        def cond(st: FusedTreeState):
            gains = st.best_gain
            if max_depth > 0:
                gains = jnp.where(st.leaf_depth >= max_depth, NEG_INF, gains)
            return (st.n_leaves < L) & (jnp.max(gains) > 0.0)

        def body(st: FusedTreeState, rec=None) -> FusedTreeState:
            """One split step. rec=None: split the best-gain leaf with
            its scanned best (the while_loop body). rec given: apply a
            FORCED split (leaf, feature, threshold fixed; sums computed
            from the pooled histogram) — reference ForceSplits,
            serial_tree_learner.cpp:427."""
            if rec is None:
                gains = st.best_gain
                if max_depth > 0:
                    gains = jnp.where(st.leaf_depth >= max_depth, NEG_INF,
                                      gains)
                leaf = jnp.argmax(gains).astype(i32)
                feat = st.best_feature[leaf]
                thr = st.best_thr[leaf]
                dl = st.best_dl[leaf]
                cat = st.best_cat[leaf]
                bits = st.best_bits[leaf]
                rec = dict(
                    gain=st.best_gain[leaf],
                    lg=st.best_lg[leaf], lh=st.best_lh[leaf],
                    lout=st.best_lout[leaf],
                    rg=st.best_rg[leaf], rh=st.best_rh[leaf],
                    rout=st.best_rout[leaf])
            else:
                leaf = rec["leaf"]
                feat, thr = rec["feature"], rec["threshold"]
                dl = rec["dl"]
                cat = jnp.bool_(False)
                bits = jnp.zeros(8, i32)
            node = st.n_leaves - 1
            new_leaf = st.n_leaves
            miss = self.feature_miss_bin[feat]

            # --- tree bookkeeping (Tree::Split semantics, tree.h:61) ---
            parent = st.leaf_parent[leaf]
            has_parent = parent >= 0
            pl = st.t_left[jnp.maximum(parent, 0)]
            fix_left = has_parent & (pl == ~leaf)
            t_left = st.t_left.at[jnp.maximum(parent, 0)].set(
                jnp.where(fix_left, node, st.t_left[jnp.maximum(parent, 0)]))
            t_right = st.t_right.at[jnp.maximum(parent, 0)].set(
                jnp.where(has_parent & ~fix_left, node,
                          st.t_right[jnp.maximum(parent, 0)]))
            t_feature = st.t_feature.at[node].set(feat)
            t_thr = st.t_thr.at[node].set(thr)
            t_dl = st.t_dl.at[node].set(dl)
            t_left = t_left.at[node].set(~leaf)
            t_right = t_right.at[node].set(~new_leaf)
            t_gain = st.t_gain.at[node].set(rec["gain"])
            t_ivalue = st.t_ivalue.at[node].set(st.leaf_output[leaf])
            t_iweight = st.t_iweight.at[node].set(st.leaf_sum_h[leaf])
            t_icount = st.t_icount.at[node].set(st.leaf_count_g[leaf])
            t_cat = st.t_cat.at[node].set(cat)
            t_bits = st.t_bits.at[node].set(bits)

            # --- shard-local partition; counts reduced globally ---
            start = st.leaf_start[leaf]
            count = st.leaf_count[leaf]
            count_g = st.leaf_count_g[leaf]
            new_data, nleft = self._split_step(
                st.data, start, count, feat, thr, dl, miss,
                cat=cat, bits=bits)
            nright = count - nleft
            nleft_g = self._psum(nleft)
            nright_g = count_g - nleft_g

            # smaller child by GLOBAL count — every shard must histogram
            # the same child for the psum + subtraction to be coherent
            left_smaller = nleft_g <= nright_g
            s_start = jnp.where(left_smaller, start, start + nleft)
            s_count = jnp.where(left_smaller, nleft, nright)
            hist_small = self._psum(
                self._leaf_hist_switch(new_data, s_start, s_count))

            # --- children bookkeeping ---
            lout, rout = rec["lout"], rec["rout"]
            depth = st.leaf_depth[leaf] + 1
            cmin, cmax = st.leaf_cmin[leaf], st.leaf_cmax[leaf]
            if self.use_monotone:
                monof = mono_dev[feat]
                mid = (lout + rout) / 2.0
                lcmax = jnp.where(monof > 0, jnp.minimum(cmax, mid), cmax)
                rcmin = jnp.where(monof > 0, jnp.maximum(cmin, mid), cmin)
                lcmin = jnp.where(monof < 0, jnp.maximum(cmin, mid), cmin)
                rcmax = jnp.where(monof < 0, jnp.minimum(cmax, mid), cmax)
            else:
                lcmin, lcmax, rcmin, rcmax = cmin, cmax, cmin, cmax

            leaf_start = st.leaf_start.at[new_leaf].set(start + nleft)
            leaf_count = st.leaf_count.at[leaf].set(nleft)\
                                       .at[new_leaf].set(nright)
            leaf_count_g = st.leaf_count_g.at[leaf].set(nleft_g)\
                                          .at[new_leaf].set(nright_g)
            leaf_sum_g = st.leaf_sum_g.at[leaf].set(rec["lg"])\
                                      .at[new_leaf].set(rec["rg"])
            leaf_sum_h = st.leaf_sum_h.at[leaf].set(rec["lh"])\
                                      .at[new_leaf].set(rec["rh"])
            leaf_output = st.leaf_output.at[leaf].set(lout)\
                                        .at[new_leaf].set(rout)
            leaf_depth = st.leaf_depth.at[leaf].set(depth)\
                                      .at[new_leaf].set(depth)
            leaf_parent = st.leaf_parent.at[leaf].set(node)\
                                        .at[new_leaf].set(node)
            leaf_cmin = st.leaf_cmin.at[leaf].set(lcmin).at[new_leaf].set(rcmin)
            leaf_cmax = st.leaf_cmax.at[leaf].set(lcmax).at[new_leaf].set(rcmax)

            # --- larger child: subtraction from the pooled parent (or a
            # second contiguous-slice histogram when pool-less) ---
            if self._use_hist_pool:
                hist_large = st.hist_pool[leaf] - hist_small
                hist_left = jnp.where(left_smaller, hist_small, hist_large)
                hist_right = jnp.where(left_smaller, hist_large, hist_small)
                hist_pool = st.hist_pool.at[leaf].set(hist_left)\
                                        .at[new_leaf].set(hist_right)
            else:
                l_start = jnp.where(left_smaller, start + nleft, start)
                l_count = jnp.where(left_smaller, nright, nleft)
                hist_large = self._psum(
                    self._leaf_hist_switch(new_data, l_start, l_count))
                hist_left = jnp.where(left_smaller, hist_small, hist_large)
                hist_right = jnp.where(left_smaller, hist_large, hist_small)
                hist_pool = st.hist_pool

            # --- best splits for both children (one vmapped scan) ---
            if bynode:
                mask2 = jnp.stack([feature_mask[2 * new_leaf - 1],
                                   feature_mask[2 * new_leaf]])
            else:
                mask2 = jnp.stack([feature_mask, feature_mask])
            bl, br = self._scan_two_leaves(
                jnp.stack([hist_left, hist_right]),
                jnp.stack([rec["lg"], rec["rg"]]),
                jnp.stack([rec["lh"], rec["rh"]]),
                jnp.stack([nleft_g, nright_g]),
                jnp.stack([lout, rout]),
                jnp.stack([lcmin, rcmin]),
                jnp.stack([lcmax, rcmax]), mask2, qscales=qscales)

            def upd(a, key, cast=lambda x: x):
                return a.at[leaf].set(cast(bl[key])).at[new_leaf].set(cast(br[key]))

            return FusedTreeState(
                data=new_data, n_leaves=st.n_leaves + 1,
                leaf_start=leaf_start, leaf_count=leaf_count,
                leaf_count_g=leaf_count_g,
                leaf_sum_g=leaf_sum_g, leaf_sum_h=leaf_sum_h,
                leaf_output=leaf_output, leaf_depth=leaf_depth,
                leaf_parent=leaf_parent, leaf_cmin=leaf_cmin,
                leaf_cmax=leaf_cmax,
                best_gain=upd(st.best_gain, "gain"),
                best_feature=upd(st.best_feature, "feature"),
                best_thr=upd(st.best_thr, "thr"),
                best_dl=upd(st.best_dl, "dl"),
                best_lg=upd(st.best_lg, "lg"), best_lh=upd(st.best_lh, "lh"),
                best_lcnt=upd(st.best_lcnt, "lcnt"),
                best_lout=upd(st.best_lout, "lout"),
                best_rg=upd(st.best_rg, "rg"), best_rh=upd(st.best_rh, "rh"),
                best_rcnt=upd(st.best_rcnt, "rcnt"),
                best_rout=upd(st.best_rout, "rout"),
                best_cat=upd(st.best_cat, "cat"),
                best_bits=st.best_bits.at[leaf].set(bl["bits"])
                                      .at[new_leaf].set(br["bits"]),
                hist_pool=hist_pool,
                t_feature=t_feature, t_thr=t_thr, t_dl=t_dl, t_left=t_left,
                t_right=t_right, t_gain=t_gain, t_ivalue=t_ivalue,
                t_iweight=t_iweight, t_icount=t_icount,
                t_cat=t_cat, t_bits=t_bits,
            )

        # --- user-forced splits first (BFS schedule precomputed on the
        # host; reference SerialTreeLearner::ForceSplits,
        # serial_tree_learner.cpp:427) ---
        if self._forced_sched is not None:
            f_leaf, f_feat, f_thr = self._forced_sched
            eps = S.K_EPSILON
            B = self.max_num_bin

            def forced_step(carry, k):
                st, alive = carry
                leaf = f_leaf[k]
                feat = f_feat[k]
                thr = f_thr[k]
                hist = st.hist_pool[leaf]            # [F, B, 2]
                if quant:
                    # same int->f32 boundary as the gain scans: the
                    # forced-split sums below are all-f32 arithmetic
                    hist = S.dequantize_hist(hist, qscales[0], qscales[1])
                h = jnp.sum(jnp.where(
                    (jnp.arange(F, dtype=i32) == feat)[:, None, None],
                    hist, 0.0), axis=0)              # [B, 2], no gather
                bidx = jnp.arange(B, dtype=i32)
                miss = self.feature_miss_bin[feat]
                sel = ((bidx <= thr) &
                       jnp.where(miss >= 0, bidx != miss, True))
                selm = sel.astype(f32)
                lg = jnp.sum(selm * h[:, 0])
                lh = jnp.sum(selm * h[:, 1])
                sum_g_l = st.leaf_sum_g[leaf]
                sum_h_l = st.leaf_sum_h[leaf]
                rg = sum_g_l - lg
                rh = sum_h_l - lh
                cntf = st.leaf_count_g[leaf].astype(f32) \
                    / (sum_h_l + 2 * eps)
                lcnt = jnp.floor(lh * cntf + 0.5).astype(i32)
                parent_out = st.leaf_output[leaf]
                cmin, cmax = st.leaf_cmin[leaf], st.leaf_cmax[leaf]
                # full CalculateSplittedLeafOutput semantics (L1/L2,
                # max_delta_step, path smoothing, monotone clamp) — the
                # same helper every scanned split uses
                lout = S._calc_output(lg, lh + eps, lcnt, self.split_cfg,
                                      parent_out, cmin, cmax)
                rout = S._calc_output(
                    rg, rh + eps, st.leaf_count_g[leaf] - lcnt,
                    self.split_cfg, parent_out, cmin, cmax)
                rec = dict(leaf=leaf, feature=feat, threshold=thr,
                           dl=jnp.bool_(False), gain=f32(0.0),
                           lg=lg, lh=lh, lout=lout,
                           rg=rg, rh=rh, rout=rout)
                # gate on hessian MASS per side (a truly empty side has
                # exactly zero mass; counts are hessian-derived
                # estimates in this design, ops/split.py:18, and could
                # round a small-but-real side to 0)
                ok = (alive & (lh > 1e-9) & (rh > 1e-9)
                      & (st.n_leaves < L)
                      & (st.leaf_count_g[leaf] > 0))
                st = jax.lax.cond(ok, lambda s: body(s, rec=rec),
                                  lambda s: s, st)
                # the host-precomputed slot schedule assumes every
                # earlier forced split succeeded; once one is skipped,
                # later slot ids would alias the wrong leaves — stop
                # forcing (conservative vs the reference's dynamic BFS:
                # the remaining forced splits are left to the normal
                # gain-driven loop)
                return (st, alive & ok), ()

            (st, _alive), _ = jax.lax.scan(
                forced_step, (st, jnp.bool_(True)),
                jnp.arange(f_leaf.shape[0]))

        st = jax.lax.while_loop(cond, body, st)

        tree_arrays = dict(
            n_leaves=st.n_leaves,
            split_feature=st.t_feature, threshold_bin=st.t_thr,
            default_left=st.t_dl, left_child=st.t_left, right_child=st.t_right,
            split_gain=st.t_gain, internal_value=st.t_ivalue,
            internal_weight=st.t_iweight, internal_count=st.t_icount,
            leaf_value=st.leaf_output, leaf_weight=st.leaf_sum_h,
            leaf_count=st.leaf_count_g, leaf_depth=st.leaf_depth,
            split_cat=st.t_cat, split_bits=st.t_bits,
        )
        return tree_arrays, st

    # ------------------------------------------------------------------
    def _pos_leaf_terms(self, st: FusedTreeState):
        """Sorted leaf-window starts + sort order (tiny [L] work).

        Leaves with a zero LOCAL count are excluded: they share their
        start with a sibling window (empty range), and a duplicate
        start would make the rank-among-starts trick attribute the
        rows to the empty leaf — bites on shards that hold no rows of
        some leaf (non-IID data-parallel sharding)."""
        L = self.num_leaves
        lid = jnp.arange(L, dtype=jnp.int32)
        valid = (lid < st.n_leaves) & (st.leaf_count > 0)
        starts = jnp.where(valid, st.leaf_start,
                           jnp.int32(self.layout.num_lanes) + 1)
        order = jnp.argsort(starts)
        return starts[order], order

    def _pos_leaf(self, st: FusedTreeState):
        """Leaf id per LANE via broadcast compare (no [N] gather): the
        rank of each position among the sorted starts, then the tiny
        order table applied as an equality-weighted reduction."""
        sorted_starts, order = self._pos_leaf_terms(st)
        pos = jnp.arange(self.layout.num_lanes, dtype=jnp.int32)
        k = jnp.sum(pos[:, None] >= sorted_starts[None, :],
                    axis=1).astype(jnp.int32) - 1
        k = jnp.maximum(k, 0)
        # order[k] without a per-row gather: sum_j order_j * [k == j]
        L = self.num_leaves
        lid = jnp.arange(L, dtype=jnp.int32)
        return jnp.sum(jnp.where(k[:, None] == lid[None, :],
                                 order[None, :], 0), axis=1).astype(jnp.int32)

    def _score_add_by_pos(self, st: FusedTreeState, leaf_vals):
        """Per-lane leaf value as a sum of step functions over the
        sorted window starts — fuses on the VPU, no [N] gather and no
        materialized one-hot."""
        sorted_starts, order = self._pos_leaf_terms(st)
        vals_sorted = leaf_vals[order]          # [L] gather — tiny
        d = vals_sorted - jnp.concatenate(
            [jnp.zeros((1,), jnp.float32), vals_sorted[:-1]])
        pos = jnp.arange(self.layout.num_lanes, dtype=jnp.int32)
        steps = (pos[:, None] >= sorted_starts[None, :]).astype(jnp.float32)
        return jnp.sum(steps * d[None, :], axis=1)

    # -- in-program leaf renewal (renew-tree-output objectives) --------
    def _renew_leaf_outputs(self, st: FusedTreeState, n, alpha: float,
                            weighted: bool):
        """Per-leaf weighted percentile of residuals straight off the
        leaf-ordered planar state — the device form of
        RegressionL1loss::RenewTreeOutput and the Percentile/
        WeightedPercentileFun selection (reference
        regression_objective.hpp:23-88,249).

        No sorts and no [N] gathers: residuals map to a monotone uint32
        key (sign-flipped float bits) and each leaf's order statistic is
        found by a 32-step bisection over key space. The per-step
        per-leaf counts come from one [R] compare + cumsum, read back at
        the window boundaries — every step is a fused VPU pass, and the
        counts psum across shards so the refit is exact under the
        sharded data-parallel learner.

        Tie semantics (weighted mode): the reference walks the stable
        sort order and takes the first item whose cumulative weight
        minus half its own weight crosses alpha*total; value-space
        bisection lumps equal-valued items into one mass and uses the
        half-mass rule. For distinct residuals (the generic case) the
        two rules select the same element; under exact ties they can
        pick adjacent values."""
        Ly = self.layout
        lanes = jnp.arange(Ly.num_lanes, dtype=jnp.int32)
        realm = lanes < jnp.asarray(n, jnp.int32)
        resid = (plane.get_f32(st.data, Ly.label)
                 - plane.get_f32(st.data, Ly.score))
        i = jax.lax.bitcast_convert_type(resid, jnp.int32)
        u = jax.lax.bitcast_convert_type(i, jnp.uint32)
        ukey = jnp.where(i < 0, ~u, u | jnp.uint32(0x80000000))

        sorted_starts, order = self._pos_leaf_terms(st)

        def per_lane(v_leaf, dtype):
            """Broadcast a [L] per-leaf vector to lanes by window —
            telescoping step sums, exact in modular uint32 arithmetic."""
            vs = v_leaf[order].astype(dtype)
            d = vs - jnp.concatenate([jnp.zeros((1,), dtype), vs[:-1]])
            steps = (lanes[:, None] >= sorted_starts[None, :])
            return jnp.sum(jnp.where(steps, d[None, :], 0), axis=1)

        ends = st.leaf_start + st.leaf_count
        sidx = jnp.maximum(st.leaf_start, 1) - 1

        def seg_sums(c):
            """Per-leaf window sums of a [R] vector via one cumsum.
            Shard-locally EMPTY windows at start 0 would read lane 0's
            value (ends==0 -> cs[0]); zero them explicitly BEFORE the
            psum so no shard contributes phantom mass."""
            cs = jnp.cumsum(c)
            lo = jnp.where(st.leaf_start > 0, cs[sidx], 0)
            raw = cs[jnp.maximum(ends, 1) - 1] - lo
            return self._psum(jnp.where(st.leaf_count > 0, raw, 0))

        L = self.num_leaves
        lid = jnp.arange(L, dtype=jnp.int32)
        cnt = st.leaf_count_g
        valid = (lid < st.n_leaves) & (cnt > 0)

        def bisect(pred_of_mid, shape):
            """Smallest uint32 key with monotone pred(mid) true."""
            lo = jnp.zeros(shape, jnp.uint32)
            hi = jnp.full(shape, 0xFFFFFFFF, jnp.uint32)

            def step(_, lh):
                lo, hi = lh
                mid = lo + (hi - lo) // jnp.uint32(2)
                p = pred_of_mid(mid)
                return (jnp.where(p, lo, mid + jnp.uint32(1)),
                        jnp.where(p, mid, hi))

            lo, hi = jax.lax.fori_loop(0, 32, step, (lo, hi))
            return lo

        def key_to_f32(k):
            neg = k < jnp.uint32(0x80000000)
            u_orig = jnp.where(neg, ~k, k & jnp.uint32(0x7FFFFFFF))
            return jax.lax.bitcast_convert_type(u_orig, jnp.float32)

        def order_stat_keys(targets):
            """Integer-exact order statistics: per-leaf uint32 keys at
            ascending 0-indexed ``targets`` [L, T]. Counts are int32
            cumsums, so these bisections cannot jitter."""
            T_ = targets.shape[1]

            def pred(mid):
                cm = jnp.stack([per_lane(mid[:, t], jnp.uint32)
                                for t in range(T_)], axis=0)   # [T, R]
                le = (ukey[None, :] <= cm) & realm[None, :]
                cnts = jnp.stack(
                    [seg_sums(le[t].astype(jnp.int32)) for t in range(T_)],
                    axis=1)                                    # [L, T]
                return cnts >= targets + 1

            return bisect(pred, targets.shape)

        if not weighted:
            # PercentileFun: DESCENDING selection at float_pos =
            # (1-alpha)*cnt via ArgMaxAtK — in ascending ranks the two
            # selected order statistics are cnt-pos and cnt-pos-1, and
            # the result is d[pos-1] - (d[pos-1]-d[pos])*bias. Edge
            # rules (pos<1 -> max, pos>=cnt -> min, cnt<=1 -> the
            # value) mirror the macro exactly.
            cf = cnt.astype(jnp.float32)
            float_pos = (1.0 - jnp.float32(alpha)) * cf
            pos = jnp.floor(float_pos).astype(jnp.int32)
            bias = float_pos - pos.astype(jnp.float32)
            edge_max = pos < 1                     # includes cnt <= 1
            edge_min = pos >= cnt
            r_hi = jnp.clip(cnt - pos, 0, jnp.maximum(cnt - 1, 0))
            r_lo = jnp.clip(cnt - pos - 1, 0, jnp.maximum(cnt - 1, 0))
            r_hi = jnp.where(edge_max, jnp.maximum(cnt - 1, 0),
                             jnp.where(edge_min, 0, r_hi))
            r_lo = jnp.where(edge_max | edge_min, r_hi, r_lo)
            bias = jnp.where(edge_max | edge_min, 0.0, bias)
            keys = order_stat_keys(jnp.stack([r_hi, r_lo], axis=1))
            v1 = key_to_f32(keys[:, 0])            # d[pos-1]
            v2 = key_to_f32(keys[:, 1])            # d[pos]
            out = v1 - (v1 - v2) * bias
        else:
            # WeightedPercentileFun: ascending weighted CDF,
            # pos = upper_bound(cdf, alpha*total); returns the value at
            # pos, except the (next-step-weight >= 1.0) branch which
            # interpolates with a negative factor — mirrored as-is.
            # The value-space bisection uses f32 mass sums (the [R]
            # cumsum carries ~1e-7*prefix rounding and the host uses
            # f64), so the crossing is then SNAPPED to a true data key
            # with integer-exact rank bisections; under exact residual
            # ties the per-index CDF is approximated at value
            # granularity (tie block = one mass).
            w = plane.get_f32(st.data, Ly.weight)
            w = jnp.where(realm, w, 0.0)
            wtot = seg_sums(w)
            thresh = jnp.float32(alpha) * wtot                 # [L]

            def wle_at(mid):
                cm = per_lane(mid, jnp.uint32)                 # [R]
                return seg_sums(jnp.where((ukey <= cm) & realm, w, 0.0))

            b = bisect(lambda mid: wle_at(mid) > thresh, (L,))
            # snap to the data key at the crossing: rank = count(< b),
            # clamped like the reference's pos = min(pos, cnt-1)
            cmb = per_lane(b, jnp.uint32)
            c_lt = seg_sums(((ukey < cmb) & realm).astype(jnp.int32))
            c_lt = jnp.minimum(c_lt, jnp.maximum(cnt - 1, 0))
            prev_rank = jnp.maximum(c_lt - 1, 0)
            keys = order_stat_keys(
                jnp.stack([c_lt, prev_rank], axis=1))
            v2k, v1k = keys[:, 0], keys[:, 1]
            v2 = key_to_f32(v2k)                   # value at pos
            v1 = key_to_f32(v1k)                   # value at pos-1
            # masses at the snapped key: cdf[pos] and the next step
            cm2 = per_lane(v2k, jnp.uint32)
            wle2 = seg_sums(jnp.where((ukey <= cm2) & realm, w, 0.0))
            c_le2 = seg_sums(((ukey <= cm2) & realm).astype(jnp.int32))
            nxt = order_stat_keys(
                jnp.minimum(c_le2, jnp.maximum(cnt - 1, 0))[:, None])[:, 0]
            cm3 = per_lane(nxt, jnp.uint32)
            wle3 = seg_sums(jnp.where((ukey <= cm3) & realm, w, 0.0))
            wnext = wle3 - wle2
            pos0 = c_lt == 0
            islast = c_le2 >= cnt
            interp = (~pos0) & (~islast) & (wnext >= 1.0)
            out_i = (thresh - wle2) / jnp.where(wnext == 0, 1.0, wnext) \
                * (v2 - v1) + v1
            out = jnp.where(interp, out_i, v2)
        return jnp.where(valid, out, 0.0).astype(jnp.float32)

    def _renew_quant_leaves(self, st: FusedTreeState, n):
        """Leaf values from the RAW f32 gradient/hessian sums after a
        quantized-gradient tree search (the reference's
        RenewIntGradTreeOutput, gradient_discretizer.cpp) — the tree
        STRUCTURE keeps the quantized split decisions, the leaf OUTPUTS
        drop the rounding error. Raw grads come from persistent_grads on
        the final state's score/label planes (values unchanged by the
        growth loop, only lane-permuted with the rows), then per-leaf
        window sums via the one-cumsum trick of _renew_leaf_outputs."""
        Ly = self.layout
        lanes = jnp.arange(Ly.num_lanes, dtype=jnp.int32)
        realm = lanes < jnp.asarray(n, jnp.int32)
        score = plane.get_f32(st.data, Ly.score)
        label = plane.get_f32(st.data, Ly.label)
        weight = plane.get_f32(st.data, Ly.weight) if Ly.weight >= 0 \
            else None
        g, h = self.objective.persistent_grads(score, label, weight)
        g = jnp.where(realm, g, 0.0)
        h = jnp.where(realm, h, 0.0)

        ends = st.leaf_start + st.leaf_count
        sidx = jnp.maximum(st.leaf_start, 1) - 1

        def seg_sums(c):
            # per-leaf window sums of a [R] vector via one cumsum;
            # shard-locally empty windows zeroed BEFORE the psum (see
            # _renew_leaf_outputs)
            cs = jnp.cumsum(c)
            lo = jnp.where(st.leaf_start > 0, cs[sidx], 0.0)
            raw = cs[jnp.maximum(ends, 1) - 1] - lo
            return self._psum(jnp.where(st.leaf_count > 0, raw, 0.0))

        sg = seg_sums(g)
        sh = seg_sums(h)
        cfg = self.split_cfg
        # CalculateSplittedLeafOutput's basic form (threshold_l1 is the
        # identity at lambda_l1=0); the monotone bounds carried in the
        # state still clamp the renewed values
        out = -S.threshold_l1(sg, cfg.lambda_l1) \
            / (sh + cfg.lambda_l2 + S.K_EPSILON)
        if cfg.max_delta_step > 0:
            out = jnp.clip(out, -cfg.max_delta_step, cfg.max_delta_step)
        out = jnp.clip(out, st.leaf_cmin, st.leaf_cmax)
        lid = jnp.arange(self.num_leaves, dtype=jnp.int32)
        valid = (lid < st.n_leaves) & (st.leaf_count_g > 0)
        return jnp.where(valid, out, st.leaf_output).astype(jnp.float32)

    # ------------------------------------------------------------------
    def _grow_tree(self, codes_planes, grad, hess, perm, bag_cnt,
                   feature_mask, bins_rowmajor=None, mv=None,
                   compute_score_update: bool = True):
        """Per-tree program for the non-persistent path. Returns
        (tree arrays dict, leaf_of_row [n] in ORIGINAL row order or
        None). ``bins_rowmajor`` is passed as a jit ARGUMENT on the
        bagging path — a self.bins closure would embed the full bin
        matrix as an HLO constant (hundreds of MB at HIGGS scale, which
        overflows remote-compile request limits). ``mv``: slot-major
        [K, n] multi-value code planes, already in the same lane order
        as ``codes_planes`` (bag-permuted on the bagging path)."""
        n = self.layout.num_rows
        data = plane.build_data(self.layout, codes_planes, grad, hess,
                                rowid=perm, mv=mv)
        ta, st = self._grow_tree_core(data, bag_cnt, feature_mask)

        leaf_of_row = None
        if compute_score_update:
            if self._score_from_partition:
                pos_leaf = self._pos_leaf(st)
                rowids = st.data[self.layout.rowid][:n]
                leaf_of_row = jnp.zeros(n, jnp.int32).at[rowids].set(
                    pos_leaf[:n], unique_indices=True)
            else:
                leaf_of_row = self.traverse_bins(ta, bins_rowmajor)
        return ta, leaf_of_row

    def grow_device(self, grad, hess, perm, bag_cnt,
                    compute_score_update=True):
        """Returns (tree_arrays dict of device arrays, leaf_of_row)."""
        if self._score_from_partition:
            cp = self.codes_planes()
            perm_dev = jnp.arange(self.layout.num_rows, dtype=jnp.int32)
            g, h = grad, hess
            bins_arg = None
            mv_arg = self._mv_dev
        else:
            # bagging: one row gather per TREE (not per split) to build
            # the bag-ordered planar pack
            perm_dev = jnp.asarray(perm, jnp.int32)
            cp = plane.build_codes_planes(self.bins[perm_dev], self.layout)
            g, h = grad[perm_dev], hess[perm_dev]
            bins_arg = self.bins
            mv_arg = (None if self._mv_dev is None
                      else self._mv_dev[:, perm_dev])
        ta, leaf = self._grow_jit(self._tables(), cp, g, h, perm_dev,
                                  jnp.int32(bag_cnt),
                                  self.feature_masks_for_tree(), bins_arg,
                                  mv_arg,
                                  compute_score_update=compute_score_update)
        if leaf is not None and leaf.shape[0] != self.actual_rows:
            # row-bucketed layout: pad lanes scattered into positions
            # >= actual_rows (build_data's arange rowid continuation)
            leaf = leaf[:self.actual_rows]
        return ta, leaf

    # -- persistent mode -----------------------------------------------
    def init_persistent_state(self, score_vec) -> jax.Array:
        """Planar state carrying label/score/row-id across iterations.
        score_vec: [n] f32 current raw scores in ORIGINAL row order."""
        assert self.persistent_capable
        aux_label, aux_weight = self.objective.persistent_aux()
        data = plane.build_data(
            self.layout, self.codes_planes(),
            jnp.zeros(self.layout.num_rows, jnp.float32),
            jnp.zeros(self.layout.num_rows, jnp.float32),
            label=jnp.asarray(aux_label, jnp.float32),
            score=jnp.asarray(score_vec, jnp.float32),
            weight=(None if aux_weight is None
                    else jnp.asarray(aux_weight, jnp.float32)),
            mv=self._mv_dev)
        # the persistent program carries the codes INSIDE `data`; the
        # cached planes copy would sit in HBM for nothing (3.9 GB at
        # the Allstate shape, next to the state and the partition
        # scratch). Drop it — the per-tree path rebuilds lazily.
        self._codes_planes_dev = None
        return data

    def _train_iter(self, data, feature_mask, shrinkage, bias,
                    n_valid=None, key=None):
        """One full boosting iteration in ONE program: gradients from
        the in-state score, tree growth, and the score update — all in
        leaf-permuted lane order (GBDT::TrainOneIter, gbdt.cpp:337,
        minus the host loop). ``n_valid`` overrides the static row
        count (traced, for per-shard row counts under shard_map).
        ``key``: per-iteration PRNG key for the stochastic rounding of
        the quantized pass (required when use_quantized_grad)."""
        Ly = self.layout
        n = jnp.int32(Ly.num_rows) if n_valid is None \
            else jnp.asarray(n_valid, jnp.int32)
        lanes = jnp.arange(Ly.num_lanes, dtype=jnp.int32)
        realm = lanes < n  # pad lanes never enter any window

        score = plane.get_f32(data, Ly.score)
        label = plane.get_f32(data, Ly.label)
        weight = plane.get_f32(data, Ly.weight) if Ly.weight >= 0 else None
        g, h = self.objective.persistent_grads(score, label, weight)
        g = jnp.where(realm, g, 0.0)
        h = jnp.where(realm, h, 0.0)
        qscales = None
        if self._quant:
            # per-iteration device quantization pass: the grad plane
            # carries the packed (qg << 16 | qh) words bitcast through
            # the f32 lanes, the hess plane zeros (the kernels unpack
            # both levels from the one word). Scales psum-max across
            # shards so every shard quantizes on the same grid and the
            # int32 histogram psums stay coherent.
            gmax = self._psum_max(jnp.max(jnp.abs(g)))
            hmax = self._psum_max(jnp.max(h))
            qg, qh, gs, hs = Q.quantize_gradients(
                g, h, self.config.num_grad_quant_bins, key,
                stochastic=self.config.stochastic_rounding,
                grad_max=gmax, hess_max=hmax)
            qscales = (gs, hs)
            packed = plane.i32_as_f32(Q.pack_gh(qg, qh))
            data = plane.set_gh_packed(data, Ly, packed)
        else:
            data = plane.set_gh(data, Ly, g, h)

        ta, st = self._grow_tree_core(data, n, feature_mask,
                                      qscales=qscales)

        renew = (self.objective.persistent_renew_spec()
                 if self.objective is not None else None)
        if renew is not None:
            # leaf refit BEFORE shrinkage, like the reference's
            # RenewTreeOutput -> Shrinkage order (gbdt.cpp:379-386)
            alpha, weighted = renew
            ta = dict(ta, leaf_value=self._renew_leaf_outputs(
                st, n, alpha, weighted))
        elif self._quant and self.config.quant_train_renew_leaf:
            # RenewIntGradTreeOutput (gradient_discretizer.cpp): leaf
            # values recomputed from the RAW f32 gradient sums so the
            # rounding error of the quantized split search never enters
            # the model output. The raw grads are recomputed from the
            # (permuted, but value-unchanged) score/label planes of the
            # FINAL state — pre-growth g/h are in pre-partition lane
            # order and would pair with the wrong windows.
            ta = dict(ta, leaf_value=self._renew_quant_leaves(st, n))

        vals = ta["leaf_value"] * shrinkage
        add = self._score_add_by_pos(st, vals.astype(jnp.float32))
        score2 = plane.get_f32(st.data, Ly.score) + add + bias
        data = plane.set_f32(st.data, Ly.score, score2)
        return data, ta

    def _next_quant_keys(self, k: int):
        """[k, 2] u32 per-iteration stochastic-rounding keys from the
        host-side iteration counter (deterministic across runs; each
        boosting iteration gets a fresh fold_in of the base key)."""
        Q.note_requantize(self.config.num_grad_quant_bins, k)
        start = self._quant_iter
        self._quant_iter += k
        return jax.vmap(
            lambda i: jax.random.fold_in(self._quant_base_key, i)
        )(jnp.arange(start, start + k, dtype=jnp.uint32))

    def train_iter_persistent(self, data, shrinkage, bias, mask=None):
        if mask is None:
            mask = self.feature_masks_for_tree()
        args = (self._tables(), data, mask, jnp.float32(shrinkage),
                jnp.float32(bias), jnp.int32(self.actual_rows))
        if self._quant:
            # extra key arg ONLY under quant: the default path's call
            # arity (and so its cached executables) stays identical
            return self._iter_jit(*args, self._next_quant_keys(1)[0])
        return self._iter_jit(*args)

    def _iters_scan_jit_build(self, k: int):
        """K boosting iterations in ONE dispatch: lax.scan over the
        persistent iteration body (traced once, so compile cost matches
        the single-iteration program). Exists because each dispatch over
        the remote-accelerator tunnel costs tens of ms of host latency —
        at K=10 the per-iteration dispatch overhead drops 10x."""
        quant = self._quant

        def run(tables, data, masks, shrinkage, n_valid, keys=None):
            with self._bind_tables(tables):
                def step(d, xs):
                    mask, key = xs if quant else (xs, None)
                    d, ta = self._train_iter(d, mask, shrinkage,
                                             jnp.float32(0.0),
                                             n_valid=n_valid, key=key)
                    return d, ta
                xs = (masks, keys) if quant else masks
                return jax.lax.scan(step, data, xs, length=k)

        from ..obs import instrument_kernel
        if self._mgr is not None:
            entry = self._mgr.shared_entry(
                f"fused/train_iters_k{k}", self._compile_signature(),
                lambda: jax.jit(run, donate_argnums=1),
                donate_argnums=(1,))
        else:
            entry = jax.jit(run, donate_argnums=1)  # tpulint: jit-ok(manager-disabled fallback branch)
        return instrument_kernel(entry, "fused",
                                 name=f"fused/train_iters_k{k}")

    def train_iters_persistent(self, data, shrinkage, masks):
        """masks: [K, F] stacked per-tree feature masks. Returns
        (data, ta_stacked) where every array in ta_stacked has a leading
        [K] axis (iteration k's tree = slice k)."""
        k = int(masks.shape[0])
        if getattr(self, "_iters_jit_k", None) is None:
            self._iters_jit_k = {}
        if k not in self._iters_jit_k:
            self._iters_jit_k[k] = self._iters_scan_jit_build(k)
        args = (self._tables(), data, masks, jnp.float32(shrinkage),
                jnp.int32(self.actual_rows))
        if self._quant:
            return self._iters_jit_k[k](*args, self._next_quant_keys(k))
        return self._iters_jit_k[k](*args)

    def _sync_scores(self, data):
        n = self.layout.num_rows
        rowids = data[self.layout.rowid][:n]
        score = plane.get_f32(data, self.layout.score)[:n]
        return jnp.zeros(n, jnp.float32).at[rowids].set(
            score, unique_indices=True)

    def sync_scores(self, data) -> jax.Array:
        """[n] f32 raw scores in original row order (one scatter — only
        runs when a host consumer asks)."""
        out = self._sync_jit(data)
        if self._num_rows_override is None \
                and out.shape[0] != self.actual_rows:
            # bucketed layout: pad lanes landed beyond the real rows
            out = out[:self.actual_rows]
        return out

    # -- checkpoint/resume (robust/checkpoint.py) ----------------------
    def persistent_lane_state(self, data):
        """(rowid_lanes, score_bits) — the two planes of the persistent
        state that evolve irrecoverably. The LANE ORDER is part of the
        numeric state (histogram and score accumulation follow it), so
        checkpointing row-order scores would not resume bit-identically;
        every other plane is a pure function of the dataset gathered
        through the rowid plane and is rebuilt on restore."""
        Ly = self.layout
        # tpulint: sync-ok(checkpoint capture; periodic, off the iteration path)
        rowid, score_bits = jax.device_get([data[Ly.rowid], data[Ly.score]])
        return np.asarray(rowid, np.int32), np.asarray(score_bits, np.int32)

    def restore_persistent_state(self, rowid_lanes, score_bits) -> jax.Array:
        """Rebuild the planar state from a checkpoint's lane planes.
        Partitions only permute lanes within [0, actual_rows), so codes
        / label / weight at lane j equal the dataset values of row
        rowid[j]; grad/hess are dead between iterations (set_gh
        overwrites them before any read); the score plane is restored
        bit-exactly from the saved words."""
        assert self.persistent_capable
        Ly = self.layout
        n = self.actual_rows
        rid = jnp.asarray(np.asarray(rowid_lanes, np.int32))
        rid_n = rid[:n]
        aux_label, aux_weight = self.objective.persistent_aux()
        cp = plane.build_codes_planes(self.bins[rid_n], Ly)
        lab = jnp.asarray(aux_label, jnp.float32)[rid_n]
        wgt = None if aux_weight is None \
            else jnp.asarray(aux_weight, jnp.float32)[rid_n]
        zeros = jnp.zeros(n, jnp.float32)
        mv = None if self._mv_dev is None else self._mv_dev[:, rid_n]
        data = plane.build_data(Ly, cp, zeros, zeros, rowid=rid,
                                label=lab, score=zeros, weight=wgt,
                                mv=mv)
        data = data.at[Ly.score].set(
            jnp.asarray(np.asarray(score_bits, np.int32)))
        self._codes_planes_dev = None
        return data

    # ------------------------------------------------------------------
    def _traverse_device(self, ta) -> jax.Array:
        return self.traverse_bins(ta, self.bins)

    def traverse_bins(self, ta, bins) -> jax.Array:
        """Leaf index for every row (incl. out-of-bag) via bin-space
        traversal of the freshly built tree (handles the OOB score path
        of GBDT::UpdateScore and validation-set score updates)."""
        n = bins.shape[0]
        node = jnp.where(ta["n_leaves"] > 1, 0, -1) * jnp.ones(n, jnp.int32)
        miss_tbl = self.feature_miss_bin
        efb = self._efb_dev

        def gather_bin(f):
            if efb is None:
                return jnp.take_along_axis(
                    bins, f[:, None], axis=1)[:, 0].astype(jnp.int32)
            group_of, offset_of, nslots_of, skip_of = efb
            codes = jnp.take_along_axis(
                bins, group_of[f][:, None], axis=1)[:, 0].astype(jnp.int32)
            rel = codes - offset_of[f]
            inband = (rel >= 0) & (rel < nslots_of[f])
            dec = rel + (rel >= skip_of[f])
            return jnp.where(inband, dec, skip_of[f]).astype(jnp.int32)

        def cond(node):
            return jnp.any(node >= 0)

        def body(node):
            nid = jnp.maximum(node, 0)
            f = ta["split_feature"][nid]
            b = gather_bin(f)
            thr = ta["threshold_bin"][nid]
            mb = miss_tbl[f]
            go_left = b <= thr
            is_missing = (b == mb) & (mb >= 0)
            go_left = jnp.where(is_missing, ta["default_left"][nid], go_left)
            if self.any_categorical:
                words = ta["split_bits"][nid]          # [N, 8]
                word = jnp.take_along_axis(
                    words, (b >> 5)[:, None], axis=1)[:, 0]
                cat_left = ((word >> (b & 31)) & 1) == 1
                go_left = jnp.where(ta["split_cat"][nid], cat_left, go_left)
            nxt = jnp.where(go_left, ta["left_child"][nid],
                            ta["right_child"][nid])
            return jnp.where(node < 0, node, nxt)

        node = jax.lax.while_loop(cond, body, node)
        return -node - 1

    # ------------------------------------------------------------------
    def _tree_mask_np(self) -> np.ndarray:
        f = self.num_features
        mask = np.ones(f, dtype=bool)
        frac = self.config.feature_fraction
        if frac < 1.0:
            k = max(1, int(np.ceil(frac * f)))
            chosen = self._col_rng.choice(f, size=k, replace=False)
            mask[:] = False
            mask[chosen] = True
        return mask

    def feature_mask_tree(self) -> jax.Array:
        if self.config.feature_fraction >= 1.0:
            # constant all-ones mask: upload ONCE. A fresh jnp.asarray
            # per iteration is a host->device transfer on the dispatch
            # path of every tree (~100 ms tunnel latency class)
            if getattr(self, "_mask_ones_dev", None) is None:
                self._mask_ones_dev = jnp.ones(self.num_features,
                                               dtype=bool)
            return self._mask_ones_dev
        return jnp.asarray(self._tree_mask_np())

    def feature_masks_for_tree(self) -> jax.Array:
        """Per-tree scan masks: [F] (by-tree sampling only) or
        [2L, F] per-scan-event masks when feature_fraction_bynode < 1
        (col_sampler.hpp GetByNode semantics: a fresh k-subset of the
        tree's selected features per candidate node; event 0 = root
        scan, events 2*new_leaf-1 / 2*new_leaf = the two children of
        the split that created leaf slot new_leaf). The shape is a
        static trace-time branch in _grow_tree_core."""
        frac = self.config.feature_fraction_bynode
        if frac >= 1.0:
            return self.feature_mask_tree()
        tm = self._tree_mask_np()
        idx = np.flatnonzero(tm)
        k = max(1, int(np.ceil(frac * len(idx))))
        E = 2 * self.num_leaves
        masks = np.zeros((E, self.num_features), dtype=bool)
        for e in range(E):
            masks[e, self._col_rng.choice(idx, size=k, replace=False)] = True
        return jnp.asarray(masks)

    def _valid_traverse_jit(self, ta, bins):
        """Jitted traversal for valid-set score updates; dispatches
        through the compile manager so same-signature boosters reuse
        one executable per valid-set shape."""
        return self._trav_jit(self._tables(), ta, bins)

    def materialize_tree(self, tree_arrays: Dict) -> Tree:
        """Device tree arrays → host Tree (real feature ids, real
        thresholds, decision_type bits). One synchronous fetch."""
        ta = {k: np.asarray(v) for k, v in tree_arrays.items()}
        k = int(ta["n_leaves"])
        tree = Tree(self.num_leaves)
        tree.num_leaves = k
        ni = max(k - 1, 0)
        mappers = self.dataset.bin_mappers
        real_idx = self.dataset.real_feature_index
        inner_feat = ta["split_feature"][:ni]
        tree.split_feature_inner[:ni] = inner_feat
        tree.split_feature[:ni] = [real_idx[f] for f in inner_feat]
        tree.threshold_in_bin[:ni] = ta["threshold_bin"][:ni]
        cat_flags = ta.get("split_cat")
        tree.threshold[:ni] = [
            0.0 if (cat_flags is not None and bool(cat_flags[i]))
            else mappers[f].bin_to_value(int(tb))
            for i, (f, tb) in enumerate(zip(inner_feat,
                                            ta["threshold_bin"][:ni]))]
        from ..models.tree import K_CATEGORICAL_MASK, _to_bitset
        dt = np.zeros(max(ni, 1), dtype=np.int8)
        cat_nodes = ta.get("split_cat")
        for i, f in enumerate(inner_feat):
            if cat_nodes is not None and bool(cat_nodes[i]):
                # reconstruct the left-category sets from the device
                # bitset (Tree::Split categorical case, tree.cpp:70-91)
                words = np.asarray(ta["split_bits"][i], dtype=np.uint32)
                bin_set = [b for b in range(mappers[f].num_bin)
                           if (words[b >> 5] >> (b & 31)) & 1]
                cat_vals = sorted(
                    mappers[f].bin_2_categorical[b] for b in bin_set
                    if mappers[f].bin_2_categorical[b] >= 0)
                dt[i] = np.int8(np.uint8(
                    K_CATEGORICAL_MASK
                    | ((mappers[f].missing_type & 3) << 2)))
                tree.threshold_in_bin[i] = tree.num_cat
                tree.threshold[i] = tree.num_cat
                tree.num_cat += 1
                bits_inner = _to_bitset(bin_set)
                bits_raw = _to_bitset(cat_vals)
                tree.cat_boundaries_inner.append(
                    tree.cat_boundaries_inner[-1] + len(bits_inner))
                tree.cat_threshold_inner.extend(bits_inner)
                tree.cat_boundaries.append(
                    tree.cat_boundaries[-1] + len(bits_raw))
                tree.cat_threshold.extend(bits_raw)
            else:
                dt[i] = np.int8((2 if ta["default_left"][i] else 0) |
                                ((mappers[f].missing_type & 3) << 2))
        tree.decision_type[:ni] = dt[:ni]
        tree.left_child[:ni] = ta["left_child"][:ni]
        tree.right_child[:ni] = ta["right_child"][:ni]
        tree.split_gain[:ni] = ta["split_gain"][:ni]
        tree.internal_value[:ni] = ta["internal_value"][:ni]
        tree.internal_weight[:ni] = ta["internal_weight"][:ni]
        tree.internal_count[:ni] = ta["internal_count"][:ni]
        tree.leaf_value[:k] = ta["leaf_value"][:k]
        tree.leaf_weight[:k] = ta["leaf_weight"][:k]
        tree.leaf_count[:k] = ta["leaf_count"][:k]
        tree.leaf_depth[:k] = ta["leaf_depth"][:k]
        return tree


class TreeArrayBatch:
    """Stacked tree arrays of K scan-batched iterations (leading [K]
    axis on every array): one device→host fetch serves all K trees."""

    def __init__(self, stack: Dict) -> None:
        self.stack = stack
        self._host: Optional[Dict] = None

    def host(self) -> Dict:
        if self._host is None:
            self._host = jax.device_get(self.stack)
        return self._host


class PendingTree:
    """Lazily-materialized device tree: keeps the raw device arrays until
    a host consumer needs a real Tree, so the training loop never blocks
    on a device→host fetch. Any Tree attribute access (num_leaves,
    to_string, leaf_index_raw, ...) transparently materializes the host
    Tree once and delegates to it, so consumers that read GBDT.models
    directly keep working without an explicit materialize pass.

    Three sourcing modes for the arrays: direct (``tree_arrays`` given),
    batched (``batch``+``index`` into a TreeArrayBatch), or queued
    (``resolver`` — a callable that dispatches the owning driver's
    queued iterations and then assigns ``batch``/``tree_arrays``)."""

    def __init__(self, grower: FusedSerialGrower,
                 tree_arrays: Optional[Dict] = None, *,
                 batch: Optional[TreeArrayBatch] = None,
                 index: int = 0, resolver=None) -> None:
        self._tree: Optional[Tree] = None
        self.grower = grower
        self._ta = tree_arrays
        self.batch = batch
        self.index = index
        self.resolver = resolver
        self.pending_shrinkage = 1.0
        self.pending_bias = 0.0
        # host-cached leaf count (GBDT._batched_tree_stats): immutable
        # once the tree is grown, so one batched fetch serves forever
        self._n_leaves_host: Optional[int] = None

    @property
    def tree_arrays(self) -> Dict:
        if self._ta is None:
            if self.batch is None and self.resolver is not None:
                self.resolver()           # dispatch queued iterations
            if self._ta is None:
                h = self.batch.host()
                self._ta = {k: v[self.index] for k, v in h.items()}
        return self._ta

    @tree_arrays.setter
    def tree_arrays(self, value: Dict) -> None:
        self._ta = value

    def apply_shrinkage(self, rate: float) -> None:
        if self._tree is not None:
            self._tree.apply_shrinkage(rate)
        else:
            self.pending_shrinkage *= rate

    def add_bias(self, val: float) -> None:
        if self._tree is not None:
            self._tree.add_bias(val)
        else:
            self.pending_bias += val

    def leaf_values_device(self):
        if self._tree is not None:
            return self._tree.leaf_values_device()
        return (self.tree_arrays["leaf_value"] * self.pending_shrinkage
                + self.pending_bias)

    def materialize(self) -> Tree:
        if self._tree is None:
            tree = self.grower.materialize_tree(self.tree_arrays)
            if self.pending_shrinkage != 1.0:
                tree.apply_shrinkage(self.pending_shrinkage)
            if self.pending_bias != 0.0:
                tree.add_bias(self.pending_bias)
            self._tree = tree
        return self._tree

    def __getattr__(self, name: str):
        # only reached when normal lookup fails → a Tree attribute;
        # materialize once and delegate. Guard against recursion during
        # unpickling/copy before __init__ has run.
        if name.startswith("__") or name in ("_tree", "grower", "tree_arrays",
                                             "_ta", "batch", "index",
                                             "resolver", "pending_shrinkage",
                                             "pending_bias"):
            raise AttributeError(name)
        return getattr(self.materialize(), name)
