"""Leaf-wise (best-first) tree grower.

TPU re-design of the reference SerialTreeLearner
(reference: src/treelearner/serial_tree_learner.cpp — Train loop at
:152-202: BeforeTrain → repeat {BeforeFindBestSplit → ConstructHistograms
→ FindBestSplitsFromHistograms (histogram subtraction for the larger
leaf at :396-404) → ArgMax over leaves → Split at :541}).

Architecture: the device executes three jitted kernels per split —
leaf-histogram (Pallas/scatter), vectorized split scan, and stable
partition — while the ~num_leaves-sized control loop stays on the host
(the reference tolerates a PCIe sync per leaf on its GPU path; the
host↔TPU latency budget here is the same shape). Kernels are
specialized on power-of-two leaf capacities so the jit cache stays
O(log N) and is reused across trees and iterations.

The histogram pool (reference feature_histogram.hpp:1061 HistogramPool)
becomes a per-leaf dict of device arrays; "smaller leaf first, larger by
subtraction" is preserved exactly.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config
from ..io.dataset import BinnedDataset
from ..io.binning import BIN_CATEGORICAL
from ..models.tree import Tree
from ..ops import histogram as H
from ..ops import quantize as Q
from ..ops import split as S
from ..obs import instrument_kernel, span as obs_span
from ..ops.partition import next_capacity, partition_leaf
from ..utils import log


class _Leaf:
    __slots__ = ("start", "count", "sum_g", "sum_h", "output", "depth",
                 "hist", "best", "cmin", "cmax")

    def __init__(self, start, count, sum_g, sum_h, output, depth,
                 hist=None, best=None, cmin=-np.inf, cmax=np.inf):
        self.start = start
        self.count = count
        self.sum_g = sum_g
        self.sum_h = sum_h
        self.output = output
        self.depth = depth
        self.hist = hist
        self.best = best
        self.cmin = cmin
        self.cmax = cmax


class SerialTreeGrower:
    """Grows one tree per call; owns the device-resident dataset view."""

    @property
    def bins(self):
        """Row-major bin matrix on device, uploaded LAZILY: the GBDT
        driver constructs this grower even when the fused path handles
        every iteration, and an eager upload strands the full [N, G]
        matrix in HBM (7.7 GB at the 13.2M x 581-bundle Allstate shape
        — the round-5 wide-sparse OOM)."""
        return self.dataset.device_bins()

    def __init__(self, dataset: BinnedDataset, config: Config) -> None:
        self.dataset = dataset
        self.config = config
        self.num_features = dataset.num_features
        mappers = dataset.bin_mappers
        self.max_num_bin = max((m.num_bin for m in mappers), default=2)
        self.any_categorical = any(m.bin_type == BIN_CATEGORICAL for m in mappers)

        monotone = [dataset.monotone_constraint(i) for i in range(self.num_features)]
        self.use_monotone = any(m != 0 for m in monotone)
        self._monotone_np = np.asarray(monotone, dtype=np.int32)
        self._mono_state = None  # per-tree, created in grow()
        penalty = list(config.feature_contri) + [1.0] * (self.num_features - len(config.feature_contri))
        # miss bin per feature for bin-space routing (NaN bin = last,
        # Zero mode = default bin; -1 = no routing). Mirrors
        # NumericalDecisionInner (tree.h:285): missing is routed by
        # default_left whenever the feature has a missing type, for any
        # num_bin; categorical routing is purely bitset membership.
        self.feature_miss_bin = np.asarray([
            -1 if m.bin_type == BIN_CATEGORICAL else
            (m.num_bin - 1 if m.missing_type == 2 else
             (m.default_bin if m.missing_type == 1 else -1))
            for m in mappers], dtype=np.int32)

        self.meta = S.FeatureMeta.build(
            num_bin=[m.num_bin for m in mappers],
            missing_type=[m.missing_type for m in mappers],
            default_bin=[m.default_bin for m in mappers],
            is_categorical=[m.bin_type == BIN_CATEGORICAL for m in mappers],
            monotone=monotone,
            penalty=[float(p) for p in penalty[:self.num_features]])
        self.split_cfg = S.SplitConfig(
            lambda_l1=config.lambda_l1, lambda_l2=config.lambda_l2,
            min_data_in_leaf=config.min_data_in_leaf,
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            max_delta_step=config.max_delta_step,
            path_smooth=config.path_smooth,
            use_monotone=self.use_monotone,
            extra_trees=config.extra_trees,
            max_cat_threshold=config.max_cat_threshold,
            cat_l2=config.cat_l2, cat_smooth=config.cat_smooth,
            max_cat_to_onehot=config.max_cat_to_onehot,
            min_data_per_group=config.min_data_per_group)

        # EFB bundle views (None on dense/trivial datasets — all hist
        # and partition calls then take the direct per-feature path)
        self._efb_dev = dataset.device_bundle_tables()
        self._efb_hist = dataset.device_hist_tables()
        self.group_max_bin = dataset.group_max_bins

        self._col_rng = np.random.RandomState(config.feature_fraction_seed)
        self._extra_rng = np.random.RandomState(config.extra_seed)
        from ..compile import get_manager
        # jit entry points register as SHARED entries keyed by (config,
        # dataset trace signature): a second grower over a same-structure
        # dataset dispatches through the first grower's executables —
        # zero retraces, zero recompiles. The builders close over THIS
        # instance, which is safe precisely because the signature pins
        # every closed-over value (signature.py contract). When the
        # dataset cannot produce a shareable signature the entries fall
        # back to a per-instance uid and skip the on-disk store.
        self._shared_sig, self._sig_store = self._serial_signature()
        self._split_jit = instrument_kernel(
            get_manager().shared_entry(
                "serial/split_scan", self._shared_sig,
                lambda: jax.jit(self._split_packed),
                store=self._sig_store),
            "split", name="serial/split_scan")
        self._interaction_sets = _parse_interaction_constraints(
            config.interaction_constraints, dataset)
        self._forced_splits = _load_forced_splits(config.forcedsplits_filename)
        # CEGB state (reference cost_effective_gradient_boosting.hpp:27
        # IsEnable + the feature-used tracking consumed by DetlaGain :66)
        self._cegb_enabled = (
            config.cegb_tradeoff != 1.0 or config.cegb_penalty_split > 0.0
            or bool(config.cegb_penalty_feature_coupled)
            or bool(config.cegb_penalty_feature_lazy))
        self._cegb_coupled_used = np.zeros(self.num_features, dtype=bool)
        # histogram_pool_size (MB; <=0 unlimited; reference
        # feature_histogram.hpp:1061): when the per-leaf histogram set
        # would not fit, drop leaf histograms after their best-split
        # scan and recompute on demand (no subtraction)
        pool_mb = config.histogram_pool_size
        need = (config.num_leaves * self.num_features
                * self.max_num_bin * 2 * 4)
        self._keep_hists = pool_mb <= 0 or need <= pool_mb * 1024 * 1024
        if not self._keep_hists:
            log.info("histogram pool (%.0f MB) exceeds histogram_pool_size"
                     "=%.0f MB: recomputing leaf histograms on demand",
                     need / 1e6, pool_mb)
        self._cur_perm = None
        self._cur_grad = None
        self._cur_hess = None
        # quantized-gradient training (ops/quantize.py): per-tree scales
        # of the current iteration, None on the f32 path
        self._quant = bool(config.use_quantized_grad)
        self._mv_state = None  # lazy multival view (see _multival_state)
        self._qscales = None
        self._quant_tree_idx = 0
        self._quant_prefetch = Q.PrefetchedQuant()

    # ------------------------------------------------------------------
    def _split_packed(self, hist, sum_g, sum_h, num_data, parent_output,
                      cmin, cmax, feature_mask, rand_thresholds,
                      cegb_delta=None, gain_scale=None, qscales=None):
        if qscales is not None:
            # integer level-sums meet float arithmetic here and only
            # here (sum_g/sum_h are already dequantized leaf totals)
            hist = S.dequantize_hist(hist, qscales[0], qscales[1])
        res = S.best_split(hist, self.meta, self.split_cfg, sum_g, sum_h,
                           num_data, parent_output, cmin, cmax,
                           feature_mask=feature_mask,
                           rand_thresholds=rand_thresholds,
                           cegb_delta=cegb_delta, gain_scale=gain_scale,
                           any_categorical=self.any_categorical)
        f = res["best_feature"]
        vec = jnp.stack([
            res["best_gain"],
            res["left_sum_gradient"][f],
            res["left_sum_hessian"][f],
            res["left_output"][f],
            res["right_sum_gradient"][f],
            res["right_sum_hessian"][f],
            res["right_output"][f],
        ])
        # integer fields kept exact (counts overflow float32 at 2^24)
        ivec = jnp.stack([
            f, res["threshold"][f],
            res["default_left"][f].astype(jnp.int32),
            res["left_count"][f], res["right_count"][f],
            res["found"][f].astype(jnp.int32),
        ]).astype(jnp.int32)
        if self.any_categorical:
            cat = jnp.concatenate([
                jnp.stack([res["cat_family"][f].astype(jnp.int32),
                           res["cat_used_bin"][f].astype(jnp.int32)]),
                res["cat_sorted_order"][f].astype(jnp.int32)])
        else:
            cat = jnp.zeros(2, jnp.int32)
        return vec, ivec, cat

    def _serial_signature(self):
        """(sig, shareable) — everything that shapes this grower's traced
        programs besides per-call shapes: the config plus the dataset
        trace signature (mapper structure, monotone constraints, EFB
        table contents — io/dataset.py trace_signature). Unlike the
        fused grower, serial entries CLOSE OVER dataset tables, so the
        dataset identity must live in the signature, not the args."""
        from ..compile import config_signature
        ds_sig, shareable = self.dataset.trace_signature()
        return {
            "config": config_signature(self.config),
            "ds": ds_sig,
            "num_features": self.num_features,
            "max_num_bin": self.max_num_bin,
            "group_max_bin": self.group_max_bin,
            "any_categorical": self.any_categorical,
            "use_monotone": self.use_monotone,
            "split_cfg": self.split_cfg,
            "efb": self._efb_dev is not None,
            "efb_hist": self._efb_hist is not None,
        }, shareable

    def _multival_state(self):
        """Lazily built row-wise multi-value view of the dataset
        (ops/multival.py): (codes [n, K] device, total_bins, group
        tables). Only materialized when hist_method picked the multival
        layout for this dataset; like the other serial entries the
        tables are CLOSED OVER — the dataset identity in _shared_sig
        pins them."""
        if self._mv_state is None:
            from ..ops import multival as MV
            ds = self.dataset
            occ = ds.occupancy
            if ds.bundles is not None:
                gnb = ds.bundles.group_num_bins
            else:
                gnb = np.asarray([m.num_bin for m in ds.bin_mappers],
                                 np.int32)
            codes, lay = MV.build_rowwise_codes(ds.bins, gnb,
                                                occ.default_code)
            self._mv_state = (jnp.asarray(codes), lay.total_bins,
                              MV.group_tables(gnb, occ.default_code))
        return self._mv_state

    @functools.lru_cache(maxsize=64)
    def _hist_fn(self, capacity: int):
        B = self.max_num_bin
        Bg = self.group_max_bin
        efb_hist = self._efb_hist
        method = H.hist_method(self.config, self.dataset)

        if method == "multival_pallas":
            from ..ops import multival as MV
            codes_dev, total_bins, tables = self._multival_state()

            def fn(bins, perm, start, count, grad, hess):
                # ``bins`` ignored: the multival path reads the packed
                # present-code view instead of the [n, G] bin matrix
                flat = MV.leaf_histogram_multival(
                    codes_dev, perm, start, count, grad, hess,
                    capacity, total_bins)
                ghist = MV.group_hist_from_flat(flat, tables)
                if efb_hist is None:
                    return ghist
                from ..io.efb import per_feature_hist
                total = flat[-1]
                return per_feature_hist(ghist, efb_hist, total[0],
                                        total[1])
        else:
            def fn(bins, perm, start, count, grad, hess):
                if efb_hist is None:
                    return H.leaf_histogram(bins, perm, start, count,
                                            grad, hess, capacity, B,
                                            method=method)
                # bundle-space histogram over G << F columns, then gather
                # to per-feature space with FixHistogram mfb
                # reconstruction
                from ..io.efb import per_feature_hist
                ghist = H.leaf_histogram(bins, perm, start, count, grad,
                                         hess, capacity, Bg,
                                         method=method)
                total = ghist[0].sum(axis=0)  # every row in one code
                return per_feature_hist(ghist, efb_hist, total[0],
                                        total[1])
        from ..compile import get_manager
        sig = dict(self._shared_sig, capacity=capacity,
                   hist_method=method)
        return instrument_kernel(
            get_manager().shared_entry("serial/leaf_histogram", sig,
                                       lambda: jax.jit(fn),
                                       store=self._sig_store),
            "hist", name="serial/leaf_histogram")

    @functools.lru_cache(maxsize=64)
    def _partition_fn(self, capacity: int):
        efb = self._efb_dev
        from ..compile import get_manager

        def fn(bins, perm, start, count, feature, threshold, default_left,
               miss_bin, is_cat, cat_bitset):
            return partition_leaf(bins, perm, start, count, feature,
                                  threshold, default_left, miss_bin, is_cat,
                                  cat_bitset, capacity, efb=efb)
        sig = dict(self._shared_sig, capacity=capacity)
        entry = get_manager().shared_entry("serial/partition_leaf", sig,
                                           lambda: jax.jit(fn),
                                           store=self._sig_store)
        return instrument_kernel(entry, "partition",
                                 name="serial/partition_leaf")

    # ------------------------------------------------------------------
    def _feature_mask_tree(self) -> np.ndarray:
        """Per-tree feature_fraction sampling (reference
        col_sampler.hpp:20 ResetByTree)."""
        f = self.num_features
        mask = np.ones(f, dtype=bool)
        frac = self.config.feature_fraction
        if frac < 1.0:
            k = max(1, int(np.ceil(frac * f)))
            chosen = self._col_rng.choice(f, size=k, replace=False)
            mask[:] = False
            mask[chosen] = True
        return mask

    def _feature_mask_node(self, tree_mask: np.ndarray,
                           branch_features: Optional[set]) -> np.ndarray:
        """Per-node sampling + interaction constraints (reference
        col_sampler.hpp GetByNode)."""
        mask = tree_mask
        frac = self.config.feature_fraction_bynode
        if frac < 1.0:
            idx = np.flatnonzero(mask)
            k = max(1, int(np.ceil(frac * len(idx))))
            chosen = self._col_rng.choice(idx, size=k, replace=False)
            mask = np.zeros_like(mask)
            mask[chosen] = True
        if self._interaction_sets and branch_features is not None:
            allowed = np.zeros_like(mask)
            for s in self._interaction_sets:
                if branch_features <= s:
                    for fi in s:
                        if fi < len(allowed):
                            allowed[fi] = True
            mask = mask & allowed
        return mask

    def _cegb_delta(self, leaf: "_Leaf"):
        """Cost-Effective Gradient Boosting gain penalty per feature
        (reference cost_effective_gradient_boosting.hpp DetlaGain :66:
        tradeoff * (penalty_split * n_leaf + coupled penalty if the
        feature is unused so far + lazy penalty per not-yet-used data;
        lazy is approximated at leaf granularity here)."""
        if not self._cegb_enabled:
            return None
        cfg = self.config
        delta = np.full(self.num_features,
                        cfg.cegb_penalty_split * leaf.count, dtype=np.float64)
        coupled = cfg.cegb_penalty_feature_coupled
        lazy = cfg.cegb_penalty_feature_lazy
        for i, real in enumerate(self.dataset.real_feature_index):
            if coupled and real < len(coupled) and not self._cegb_coupled_used[i]:
                delta[i] += coupled[real]
            if lazy and real < len(lazy):
                delta[i] += lazy[real] * leaf.count
        return jnp.asarray(delta * cfg.cegb_tradeoff, jnp.float32)

    def _rand_thresholds(self) -> Optional[jax.Array]:
        if not self.config.extra_trees:
            return None
        nb = np.asarray([m.num_bin for m in self.dataset.bin_mappers])
        hi = np.maximum(nb - 2, 1)
        r = self._extra_rng.randint(0, 1 << 30, size=self.num_features) % hi
        return jnp.asarray(r.astype(np.int32))

    # ------------------------------------------------------------------
    def prefetch_quantize(self, grad: jax.Array, hess: jax.Array) -> None:
        """Dispatch the quantization pass for an upcoming grow() call
        NOW, up to two trees ahead of consumption (the double buffer in
        ops/quantize.py PrefetchedQuant). Key indices advance exactly
        as the inline path's would, so the stochastic-rounding draws
        are bit-identical; grow() falls back to the inline pass when
        its arguments don't match a slot. No-op on the f32 path."""
        if not self._quant or self._quant_prefetch.full:
            return
        cfg = self.config
        idx = self._quant_tree_idx + len(self._quant_prefetch)
        key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.objective_seed ^ 0x51A7), idx)
        self._quant_prefetch.push(idx, grad, hess, Q.quantize_gradients(
            grad, hess, cfg.num_grad_quant_bins, key,
            cfg.stochastic_rounding))

    def grow(self, grad: jax.Array, hess: jax.Array, perm: jax.Array,
             num_data: int) -> Tree:
        """Train one tree (reference SerialTreeLearner::Train,
        serial_tree_learner.cpp:152-202).

        grad/hess: [N] device arrays (already bag-masked: zero outside
        the bag); perm: [N] permutation with the bag's rows in
        [0, num_data).
        """
        cfg = self.config
        tree = Tree(cfg.num_leaves, track_branch_features=bool(self._interaction_sets))
        tree_mask = self._feature_mask_tree()
        rand_thr = self._rand_thresholds()
        if self.use_monotone:
            from .monotone import MonotoneState
            self._mono_state = MonotoneState(
                cfg.monotone_constraints_method, cfg.num_leaves,
                self._monotone_np)

        raw_grad, raw_hess = grad, hess
        self._qscales = None
        if self._quant:
            # one quantization pass per tree; histograms, the pool, and
            # subtraction then run in exact int32 level space. The pass
            # itself usually dispatched ahead (prefetch_quantize) — the
            # inline fallback is bit-identical (same fold_in key)
            with obs_span("gradient quantization", phase="quantize"):
                Q.note_requantize(cfg.num_grad_quant_bins)
                pre = self._quant_prefetch.pop_match(
                    self._quant_tree_idx, grad, hess)
                if pre is None:
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(cfg.objective_seed ^ 0x51A7),
                        self._quant_tree_idx)
                    pre = Q.quantize_gradients(
                        grad, hess, cfg.num_grad_quant_bins, key,
                        cfg.stochastic_rounding)
                self._quant_tree_idx += 1
                grad, hess, gs, hs = pre
                self._qscales = (gs, hs)

        self._cur_perm, self._cur_grad, self._cur_hess = perm, grad, hess
        root = _Leaf(0, num_data, 0.0, 0.0, 0.0, 0)
        cap = next_capacity(num_data)
        root.hist = self._hist_fn(cap)(self.bins, perm, 0, num_data, grad, hess)
        # root sums from the histogram (every row lands in exactly one bin
        # of feature 0), so out-of-bag rows never contribute — the
        # reference computes these in LeafSplits::Init over bag indices
        if self._quant:
            # leaf totals live in dequantized f32 units host-side; ONE
            # transfer for the two quant scales and both root sums
            # tpulint: sync-ok(per-tree root stats, single batched transfer)
            gsh, hsh, sg, sh = jax.device_get(
                (self._qscales[0], self._qscales[1],
                 jnp.sum(root.hist[0, :, 0]), jnp.sum(root.hist[0, :, 1])))
            self._qscales_host = (float(gsh), float(hsh))
            root.sum_g = float(sg) * self._qscales_host[0]
            root.sum_h = float(sh) * self._qscales_host[1]
        else:
            # tpulint: sync-ok(per-tree root stats, single batched transfer)
            sg, sh = jax.device_get((jnp.sum(root.hist[0, :, 0]),
                                     jnp.sum(root.hist[0, :, 1])))
            root.sum_g, root.sum_h = float(sg), float(sh)
        leaves: Dict[int, _Leaf] = {0: root}
        if self._forced_splits is not None:
            perm = self._apply_forced_splits(tree, leaves, perm, grad, hess)
        for leaf in leaves.values():
            leaf.best = self._compute_best(
                leaf, tree_mask, set() if self._interaction_sets else None,
                rand_thr)
            if not self._keep_hists:
                leaf.hist = None

        for _ in range(cfg.num_leaves - 1 - tree.num_nodes):
            # pick the globally-best leaf (reference ArgMax at :188)
            best_leaf, best_gain = -1, 0.0
            for lid, leaf in leaves.items():
                if leaf.best is None:
                    continue
                if cfg.max_depth > 0 and leaf.depth >= cfg.max_depth:
                    continue
                if leaf.best["gain"] > best_gain:
                    best_leaf, best_gain = lid, leaf.best["gain"]
            if best_leaf < 0:
                break
            perm = self._split_leaf(tree, leaves, best_leaf, perm, grad, hess,
                                    tree_mask, rand_thr)

        self.last_perm = perm
        if self._quant and cfg.quant_train_renew_leaf:
            self._renew_leaf_values(tree, leaves, perm, raw_grad, raw_hess)
        return tree

    def _renew_leaf_values(self, tree: Tree, leaves: Dict[int, _Leaf],
                           perm, grad, hess) -> None:
        """Refit leaf outputs from the EXACT f32 grad/hess sums after a
        quantized growth (reference quant_train_renew_leaf,
        gradient_discretizer RenewIntGradTreeOutput): the tree structure
        keeps the quantized decisions, the leaf values drop the
        level-rounding error. Window sums come from one device cumsum
        over the final leaf-ordered permutation; only per-leaf boundary
        prefix values transfer to the host."""
        items = [(lid, lf) for lid, lf in leaves.items() if lf.count > 0]
        if not items:
            return
        cg = jnp.cumsum(grad[perm])
        ch = jnp.cumsum(hess[perm])
        ends = jnp.asarray([lf.start + lf.count - 1 for _, lf in items],
                           jnp.int32)
        los = np.asarray([lf.start - 1 for _, lf in items])
        lo_idx = jnp.asarray(np.maximum(los, 0), jnp.int32)
        # tpulint: sync-ok(per-tree leaf renewal, already one batched transfer)
        ge, he, gl, hl = jax.device_get(
            (cg[ends], ch[ends], cg[lo_idx], ch[lo_idx]))
        has_lo = los >= 0
        sum_g = np.asarray(ge, np.float64) - np.where(has_lo, gl, 0.0)
        sum_h = np.asarray(he, np.float64) - np.where(has_lo, hl, 0.0)
        cfg = self.config
        for (lid, lf), g, h in zip(items, sum_g, sum_h):
            if cfg.lambda_l1 > 0:
                g = np.sign(g) * max(abs(g) - cfg.lambda_l1, 0.0)
            out = -g / (h + cfg.lambda_l2 + S.K_EPSILON)
            if cfg.max_delta_step > 0:
                out = float(np.clip(out, -cfg.max_delta_step,
                                    cfg.max_delta_step))
            if self.use_monotone:
                out = float(np.clip(out, lf.cmin, lf.cmax))
            tree.leaf_value[lid] = float(out)

    # ------------------------------------------------------------------
    def _compute_best(self, leaf: _Leaf, tree_mask: np.ndarray,
                      branch_features: Optional[set],
                      rand_thr) -> Optional[dict]:
        if leaf.count < 2 * self.config.min_data_in_leaf \
                or leaf.sum_h < 2 * self.config.min_sum_hessian_in_leaf:
            return None
        drop_after = False
        if leaf.hist is None:
            # pool-capped mode: recompute this leaf's histogram from its
            # still-valid permutation window (reference HistogramPool
            # miss -> reconstruct)
            cap = next_capacity(leaf.count)
            leaf.hist = self._hist_fn(cap)(
                self.bins, self._cur_perm, jnp.int32(leaf.start),
                jnp.int32(leaf.count), self._cur_grad, self._cur_hess)
            drop_after = True
        mask = self._feature_mask_node(tree_mask, branch_features)
        cegb = self._cegb_delta(leaf)
        scale = None
        if self.use_monotone and self.config.monotone_penalty > 0:
            from .monotone import monotone_penalty_factor
            fac = monotone_penalty_factor(leaf.depth,
                                          self.config.monotone_penalty)
            scale = jnp.asarray(
                np.where(self._monotone_np != 0, fac, 1.0), jnp.float32)
        args = (
            leaf.hist, jnp.float32(leaf.sum_g), jnp.float32(leaf.sum_h),
            jnp.int32(leaf.count), jnp.float32(leaf.output),
            jnp.float32(leaf.cmin), jnp.float32(leaf.cmax),
            jnp.asarray(mask), rand_thr if rand_thr is not None
            else jnp.zeros(self.num_features, jnp.int32), cegb, scale)
        if self._qscales is not None:
            vec, ivec, cat = self._split_jit(*args, self._qscales)
        else:
            vec, ivec, cat = self._split_jit(*args)
        # per-leaf best-split readback: ONE transfer for the packed
        # split vector, its int lanes, and the categorical block
        # tpulint: sync-ok(per-leaf split readback, single batched transfer)
        vec, ivec, cat = jax.device_get((vec, ivec, cat))
        v = np.asarray(vec, dtype=np.float64)
        iv = np.asarray(ivec, dtype=np.int64)
        if drop_after:
            leaf.hist = None
        if not iv[5] or not np.isfinite(v[0]) or v[0] <= 0.0:
            return None
        best = {
            "feature": int(iv[0]), "gain": float(v[0]), "threshold": int(iv[1]),
            "default_left": bool(iv[2]), "left_sum_gradient": float(v[1]),
            "left_sum_hessian": float(v[2]), "left_count": int(iv[3]),
            "left_output": float(v[3]), "right_sum_gradient": float(v[4]),
            "right_sum_hessian": float(v[5]), "right_count": int(iv[4]),
            "right_output": float(v[6]),
        }
        if self.any_categorical:
            c = np.asarray(cat)
            best["cat_family"] = int(c[0])
            best["cat_used_bin"] = int(c[1])
            best["cat_sorted_order"] = c[2:]
        return best

    def _split_leaf(self, tree: Tree, leaves: Dict[int, _Leaf], lid: int,
                    perm, grad, hess, tree_mask, rand_thr) -> None:
        """Apply the stored best split (reference SplitInner,
        serial_tree_learner.cpp:541-660)."""
        leaf = leaves[lid]
        best = leaf.best
        fi = best["feature"]
        mapper = self.dataset.bin_mappers[fi]
        real_feature = self.dataset.real_feature_index[fi]
        is_cat = mapper.bin_type == BIN_CATEGORICAL
        mono = self.dataset.monotone_constraint(fi)
        if self._mono_state is not None:
            self._mono_state.before_split(tree, lid, mono)

        if is_cat:
            bin_set = self._cat_bins(best)
            bitset_bins = np.zeros((self.max_num_bin + 31) // 32, dtype=np.uint32)
            for b in bin_set:
                bitset_bins[b // 32] |= np.uint32(1 << (b % 32))
            cat_vals = sorted(mapper.bin_2_categorical[b] for b in bin_set
                              if mapper.bin_2_categorical[b] >= 0)
            right_leaf = tree.split_categorical(
                lid, fi, real_feature, sorted(bin_set), cat_vals,
                best["left_output"], best["right_output"],
                best["left_count"], best["right_count"],
                best["left_sum_hessian"], best["right_sum_hessian"],
                best["gain"], mapper.missing_type)
            cat_bitset_dev = jnp.asarray(bitset_bins)
            thr, dl, mb = 0, False, -1
        else:
            threshold_real = mapper.bin_to_value(best["threshold"])
            right_leaf = tree.split(
                lid, fi, real_feature, best["threshold"], threshold_real,
                best["left_output"], best["right_output"],
                best["left_count"], best["right_count"],
                best["left_sum_hessian"], best["right_sum_hessian"],
                best["gain"], mapper.missing_type, best["default_left"])
            cat_bitset_dev = jnp.zeros(1, jnp.uint32)
            thr, dl, mb = best["threshold"], best["default_left"], \
                int(self.feature_miss_bin[fi])

        cap = next_capacity(leaf.count)
        new_perm, left_count = self._partition_fn(cap)(
            self.bins, perm, jnp.int32(leaf.start), jnp.int32(leaf.count),
            jnp.int32(fi), jnp.int32(thr), bool(dl), jnp.int32(mb),
            bool(is_cat), cat_bitset_dev)
        # tpulint: sync-ok(partition count steers the host grow loop)
        lc = int(left_count)
        rc = leaf.count - lc

        # monotone constraint propagation (reference
        # monotone_constraints.hpp Basic/IntermediateLeafConstraints)
        lcmin, lcmax, rcmin, rcmax = leaf.cmin, leaf.cmax, leaf.cmin, leaf.cmax
        updated_leaves: List[int] = []
        if self._mono_state is not None:
            ms = self._mono_state
            updated_leaves = ms.update(
                tree, lid, right_leaf, mono, not is_cat,
                best["left_output"], best["right_output"], fi,
                best["threshold"],
                lambda l: l in leaves and leaves[l].best is not None)
            lcmin, lcmax = ms.cmin[lid], ms.cmax[lid]
            rcmin, rcmax = ms.cmin[right_leaf], ms.cmax[right_leaf]

        left = _Leaf(leaf.start, lc, best["left_sum_gradient"],
                     best["left_sum_hessian"], best["left_output"],
                     leaf.depth + 1, cmin=lcmin, cmax=lcmax)
        right = _Leaf(leaf.start + lc, rc, best["right_sum_gradient"],
                      best["right_sum_hessian"], best["right_output"],
                      leaf.depth + 1, cmin=rcmin, cmax=rcmax)

        # histogram: smaller child directly, larger by subtraction
        # (reference serial_tree_learner.cpp:396-404); pool-capped mode
        # computes both directly and keeps nothing
        self._cur_perm = new_perm
        smaller, larger = (left, right) if lc <= rc else (right, left)
        scap = next_capacity(max(smaller.count, 1))
        smaller.hist = self._hist_fn(scap)(
            self.bins, new_perm, jnp.int32(smaller.start),
            jnp.int32(smaller.count), grad, hess)
        if self._keep_hists and leaf.hist is not None:
            larger.hist = leaf.hist - smaller.hist
        else:
            lcap = next_capacity(max(larger.count, 1))
            larger.hist = self._hist_fn(lcap)(
                self.bins, new_perm, jnp.int32(larger.start),
                jnp.int32(larger.count), grad, hess)
        leaf.hist = None

        branches = None
        if self._interaction_sets:
            # branch features are tracked as real ids; constraints are in
            # inner-feature space
            branches = {self.dataset.inner_feature_index[f]
                        for f in tree.branch_features[lid]
                        if f in self.dataset.inner_feature_index}
        left.best = self._compute_best(left, tree_mask, branches, rand_thr)
        right.best = self._compute_best(right, tree_mask, branches, rand_thr)
        if not self._keep_hists:
            left.hist = None
            right.hist = None

        leaves[lid] = left
        leaves[right_leaf] = right
        # intermediate monotone mode: leaves whose bounds tightened must
        # re-search their best split (reference serial_tree_learner.cpp
        # :650-658 consuming leaves_need_update)
        for ul in updated_leaves:
            if ul in (lid, right_leaf):
                continue
            u = leaves[ul]
            u.cmin = self._mono_state.cmin[ul]
            u.cmax = self._mono_state.cmax[ul]
            ub = None
            if self._interaction_sets:
                ub = {self.dataset.inner_feature_index[f]
                      for f in tree.branch_features[ul]
                      if f in self.dataset.inner_feature_index}
            u.best = self._compute_best(u, tree_mask, ub, rand_thr)
        if self._cegb_enabled:
            self._cegb_coupled_used[fi] = True
        return new_perm

    def _apply_forced_splits(self, tree: Tree, leaves: Dict[int, _Leaf],
                             perm, grad, hess):
        """Apply user-forced splits BFS-wise before the best-first loop
        (reference SerialTreeLearner::ForceSplits,
        serial_tree_learner.cpp:427; stats at a fixed threshold as in
        GatherInfoForThreshold, feature_histogram.hpp:515)."""
        from ..ops.split import K_EPSILON
        cfg = self.config
        q = [(self._forced_splits, 0)]
        while q and tree.num_leaves < cfg.num_leaves:
            node, lid = q.pop(0)
            real_f = node.get("feature")
            if real_f is None:
                continue
            inner = self.dataset.inner_feature_index.get(int(real_f))
            if inner is None:
                log.warning("Forced split on unused feature %s ignored", real_f)
                continue
            leaf = leaves[lid]
            mapper = self.dataset.bin_mappers[inner]
            thr_bin = int(mapper.value_to_bin(float(node["threshold"])))
            thr_bin = max(0, min(thr_bin, mapper.num_bin - 2))
            if leaf.hist is None:  # pool-capped mode dropped it
                cap = next_capacity(max(leaf.count, 1))
                leaf.hist = self._hist_fn(cap)(
                    self.bins, perm, jnp.int32(leaf.start),
                    jnp.int32(leaf.count), grad, hess)
            # tpulint: sync-ok(forced-splits path, config-gated and rare)
            hist = np.asarray(leaf.hist[inner], dtype=np.float64)  # [B, 2]
            if self._quant:
                # level-sums → f32 units to match leaf.sum_g/sum_h
                hist = hist * np.asarray(self._qscales_host, np.float64)
            miss = int(self.feature_miss_bin[inner])
            sel = np.arange(hist.shape[0]) <= thr_bin
            if miss >= 0:
                sel = sel & (np.arange(hist.shape[0]) != miss)
            lg = float(hist[sel, 0].sum())
            lh = float(hist[sel, 1].sum()) + K_EPSILON
            rg = leaf.sum_g - lg
            rh = leaf.sum_h + 2 * K_EPSILON - lh
            cnt_factor = leaf.count / (leaf.sum_h + 2 * K_EPSILON)
            lcnt = int(np.floor(hist[sel, 1].sum() * cnt_factor + 0.5))
            l1, l2 = cfg.lambda_l1, cfg.lambda_l2

            def out(g, h):
                s = np.sign(g) * max(0.0, abs(g) - l1) if l1 > 0 else g
                return -s / (h + l2)

            forced_best = {
                "feature": inner, "gain": 0.0, "threshold": thr_bin,
                "default_left": False,
                "left_sum_gradient": lg, "left_sum_hessian": lh - K_EPSILON,
                "left_count": lcnt, "left_output": out(lg, lh),
                "right_sum_gradient": rg, "right_sum_hessian": rh - K_EPSILON,
                "right_count": leaf.count - lcnt, "right_output": out(rg, rh),
            }
            leaf.best = forced_best
            n_before = tree.num_leaves
            perm = self._split_leaf(tree, leaves, lid, perm, grad, hess,
                                    np.ones(self.num_features, dtype=bool),
                                    None)
            right_leaf = n_before  # new leaf id assigned by Tree.split
            if "left" in node and isinstance(node["left"], dict):
                q.append((node["left"], lid))
            if "right" in node and isinstance(node["right"], dict):
                q.append((node["right"], right_leaf))
        return perm

    def _cat_bins(self, best: dict) -> List[int]:
        """Materialize the left-side category bin set from the scan's
        (family, position, sorted order) description."""
        fam = best["cat_family"]
        pos = best["threshold"]
        if fam == 0:
            return [pos]
        order = best["cat_sorted_order"]
        used = best["cat_used_bin"]
        if fam == 1:
            return [int(order[i]) for i in range(pos + 1)]
        return [int(order[used - 1 - i]) for i in range(pos + 1)]


def _load_forced_splits(filename: str):
    """Parse forcedsplits_filename JSON (reference serial_tree_learner
    constructor, serial_tree_learner.cpp:36-44)."""
    if not filename:
        return None
    import json as _json
    try:
        with open(filename) as fh:
            return _json.load(fh)
    except Exception as e:
        log.warning("Cannot load forced splits from %s: %s", filename, e)
        return None


def _parse_interaction_constraints(spec, dataset: BinnedDataset):
    """interaction_constraints -> list of allowed inner-feature-id sets
    (reference config.h interaction_constraints + col_sampler filtering)."""
    if not spec:
        return []
    groups = spec
    if isinstance(spec, str):
        import json as _json
        try:
            groups = _json.loads(spec.replace("(", "[").replace(")", "]"))
        except Exception:
            log.warning("Cannot parse interaction_constraints %r", spec)
            return []
    out = []
    for g in groups:
        inner = set()
        for f in g:
            i = dataset.inner_feature_index.get(int(f))
            if i is not None:
                inner.add(i)
        out.append(inner)
    return out
