"""Multi-chip parallel tree learners over a JAX device mesh.

TPU re-design of the reference's distributed tree learners
(reference: src/treelearner/data_parallel_tree_learner.cpp — local
histograms + Network::ReduceScatter at :169 + SyncUpGlobalBestSplit
:240; feature_parallel_tree_learner.cpp — feature shards, all data on
every machine, allreduce-max of SplitInfo; voting_parallel_tree_learner
.cpp — PV-Tree top-k voting then selective histogram reduction).

The socket/MPI collective stack (src/network/) disappears entirely: rows
are sharded over a 1-D `jax.sharding.Mesh` axis ("data"), per-shard
histograms are summed with `jax.lax.psum` (or `psum_scatter` for the
feature-sharded variant) inside `shard_map`, and the split decision is
computed replicated — the reference's Allreduce-max of packed SplitInfo
(parallel_tree_learner.h:190-213) becomes an ordinary argmax on the
already-global histogram, which is bitwise-identical on every shard.

Host control flow is identical to the serial grower; only the three
device kernels change:
- leaf histogram: shard-local gather + psum           [cross-chip: ICI]
- best split: replicated scan over global histograms  [no comm]
- partition: shard-local, per-shard (start, count)    [no comm]

Voting-parallel reduces ICI volume by only reducing histograms of the
2k vote-winning features; feature-parallel replicates rows and shards
the scan. Both reuse this class's machinery.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..utils.compat import shard_map

from ..config import Config
from ..io.dataset import BinnedDataset
from ..models.tree import Tree
from ..network import collective_span
from ..obs import instrument_kernel
from ..ops import histogram as H
from ..ops import quantize as Q
from ..ops import split as S
from ..ops.partition import next_capacity
from ..ops.partition import _decision_go_left
from ..utils import log
from .serial import SerialTreeGrower, _Leaf
from .fused import FusedSerialGrower


def shard_bag_permutation(perm, bag_cnt: int, num_shards: int,
                          rows_per_shard: int):
    """Global bag permutation -> per-shard LOCAL permutations (bag rows
    first, in order) + per-shard bag counts — the reference's
    SetBaggingData semantics applied to each machine's own row shard.
    Shard d owns global rows [d*rows_per_shard, (d+1)*rows_per_shard)."""
    D, sr = num_shards, rows_per_shard
    mask = np.zeros(D * sr, dtype=bool)
    mask[np.asarray(perm[:bag_cnt])] = True
    perm_np = np.empty((D, sr), np.int32)
    counts = np.empty(D, np.int32)
    m2 = mask.reshape(D, sr)
    for d in range(D):
        bag_local = np.flatnonzero(m2[d]).astype(np.int32)
        oob_local = np.flatnonzero(~m2[d]).astype(np.int32)
        perm_np[d] = np.concatenate([bag_local, oob_local])
        counts[d] = len(bag_local)
    return perm_np, counts


def build_mesh(config: Config) -> Mesh:
    """Mesh from tpu_mesh_shape (defaults to all devices on one axis)."""
    devices = np.asarray(jax.devices())
    if config.tpu_mesh_shape:
        shape = tuple(config.tpu_mesh_shape)
        n = int(np.prod(shape))
        if n > len(devices):
            log.fatal("tpu_mesh_shape %s needs %d devices, have %d",
                      shape, n, len(devices))
        devices = devices[:n].reshape(shape)
        axes = tuple(f"axis{i}" for i in range(len(shape) - 1)) + ("data",) \
            if len(shape) > 1 else ("data",)
        return Mesh(devices, axes)
    return Mesh(devices, ("data",))


class DataParallelTreeGrower(SerialTreeGrower):
    """Row-sharded learner (reference data_parallel_tree_learner.cpp).

    The dataset's bin matrix is laid out [D, N/D, F] (one leading shard
    axis), per-shard permutations are [D, cap_shard], and every leaf
    tracks per-shard (start, count) vectors host-side. Histogram psum
    rides ICI; everything else is shard-local.
    """

    supports_hist_subtraction = True

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        super().__init__(dataset, config)
        self.mesh = mesh if mesh is not None else build_mesh(config)
        self.num_shards = self.mesh.shape["data"]
        d = self.num_shards
        n = dataset.num_data
        self.rows_per_shard = (n + d - 1) // d
        pad = self.rows_per_shard * d - n
        bins_np = np.asarray(dataset.bins)
        if pad:
            bins_np = np.pad(bins_np, ((0, pad), (0, 0)), mode="edge")
        self._shard_valid_rows = np.full(d, self.rows_per_shard, np.int32)
        if pad:
            self._shard_valid_rows[-1] -= pad
        sharded = bins_np.reshape(d, self.rows_per_shard, -1)
        self.bins_sharded = jax.device_put(
            jnp.asarray(sharded),
            NamedSharding(self.mesh, P("data", None, None)))
        self._spec_rows = NamedSharding(self.mesh, P("data", None))

    # -- sharded kernels ------------------------------------------------
    # the voting override's local vote scan needs the per-tree
    # dequantization scales as traced args; this learner's psum does not
    _hist_takes_scales = False

    @functools.lru_cache(maxsize=64)
    def _hist_fn_sharded(self, capacity: int, packed: bool = False):
        B = self.max_num_bin
        Bg = self.group_max_bin
        efb_hist = self._efb_hist
        mesh = self.mesh
        # no dataset handle: the host-loop parallel learners always take
        # the planar/radix kernels (the multival layout is a serial- and
        # fused-learner path; see ops/histogram.py hist_method)
        method = H.hist_method(self.config)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, check_vma=False,
            in_specs=(P("data", None, None), P("data", None), P("data"),
                      P("data"), P("data", None), P("data", None)),
            out_specs=P())
        def fn(bins, perm, start, count, grad, hess):
            # leading length-1 shard axis inside the body
            h = H.leaf_histogram(bins[0], perm[0], start[0], count[0],
                                 grad[0], hess[0], capacity,
                                 Bg if efb_hist is not None else B,
                                 method=method)
            # ReduceScatter+Allgather of the reference (:169) collapses
            # to one ICI all-reduce; feature-sharded scan is a later
            # optimization once profiling justifies psum_scatter
            if packed:
                # quantized path, small leaf: both int32 level-sum
                # lanes of every cell fit 16 bits (Q.packed_rows_ok
                # checked host-side), so one packed [*, B] word psum
                # moves HALF the bytes of the [*, B, 2] reduction —
                # the integer-collective saving of the quantized
                # training paper
                hist = Q.packed_hist_to_pairs(
                    jax.lax.psum(Q.pairs_to_packed_hist(h), "data"))
            else:
                hist = jax.lax.psum(h, "data")
            # exact global leaf sums (root sums in the reference come
            # from an Allreduce of (count, Σg, Σh) tuples, :126-152);
            # int32 level sums under quantized training (host rescales)
            sg = jax.lax.psum(jnp.sum(h[0, :, 0]), "data")
            sh = jax.lax.psum(jnp.sum(h[0, :, 1]), "data")
            if efb_hist is not None:
                # EFB bundles stay sharded (round-4: no more debundling
                # under parallel learners): the bundle-space histogram
                # is psum'd, then gathered to per-feature space with the
                # mfb FixHistogram reconstruction — which needs GLOBAL
                # totals, hence after the psum (dtype-preserving, so the
                # quantized int32 reconstruction stays exact)
                from ..io.efb import per_feature_hist
                total = hist[0].sum(axis=0)
                hist = per_feature_hist(hist, efb_hist, total[0], total[1])
            return hist, sg, sh
        # the psum moves one [F, B, 2] histogram per call (f32, or int32
        # level-sums under quantized training; [F, B] packed words when
        # the leaf is small enough)
        psum_bytes = self.num_features * B * (2 if packed else 4) * 2
        from ..compile import get_manager
        return instrument_kernel(
            get_manager().jit_entry(
                f"data_parallel/leaf_histogram_c{capacity}"
                + ("_packed" if packed else ""), fn),
            "hist", name="data_parallel/leaf_histogram",
            collective=("hist_psum", psum_bytes, "data"))

    @functools.lru_cache(maxsize=64)
    def _partition_fn_sharded(self, capacity: int):
        mesh = self.mesh
        efb = self._efb_dev

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, check_vma=False,
            in_specs=(P("data", None, None), P("data", None), P("data"),
                      P("data"), P(), P(), P(), P(), P(), P()),
            out_specs=(P("data", None), P("data")))
        def fn(bins, perm, start, count, feature, threshold, default_left,
               miss_bin, is_cat, cat_bitset):
            from ..ops.partition import partition_leaf
            new_perm, lc = partition_leaf(
                bins[0], perm[0], start[0], count[0], feature, threshold,
                default_left, miss_bin, is_cat, cat_bitset, capacity,
                efb=efb)
            return new_perm[None], lc[None]
        from ..compile import get_manager
        return instrument_kernel(
            get_manager().jit_entry(
                f"data_parallel/partition_leaf_c{capacity}", fn),
            "partition", name="data_parallel/partition_leaf")

    def _hist_call(self, cap: int, total_count: int, *args):
        """Histogram + psum at the right integer width: under quantized
        training, leaves whose GLOBAL row count keeps every packed
        16-bit lane sum exact ride the halved packed-word collective;
        larger leaves escalate to the unpacked [F, B, 2] int32 psum
        (the per-leaf hist-bits escalation of the reference's
        gradient_discretizer)."""
        packed = False
        if self._qscales is not None:
            from ..obs import active as obs_active
            packed = Q.packed_rows_ok(int(total_count),
                                      self.config.num_grad_quant_bins)
            reg = obs_active()
            if reg is not None:
                if packed:
                    reg.inc("hist.quant_packed_bytes",
                            self.num_features * self.max_num_bin * 4)
                else:
                    reg.inc("hist.quant_overflow_escalations")
        fn = self._hist_fn_sharded(cap, packed)
        if self._qscales is not None and self._hist_takes_scales:
            return fn(*args, *self._qscales)
        return fn(*args)

    # -- grower ---------------------------------------------------------
    def grow(self, grad: jax.Array, hess: jax.Array, perm: jax.Array,
             num_data: int) -> Tree:
        cfg = self.config
        d = self.num_shards
        rps = self.rows_per_shard
        if self._forced_splits is not None:
            log.warning("forcedsplits_filename is not supported by the "
                        "parallel tree learners yet; ignoring")
        # shard-local views of grad/hess/perm. Bagging: each shard's
        # local permutation lists its in-bag rows first, so leaf windows
        # cover exactly the bag (mirrors SetBaggingData on the reference
        # learners); out-of-bag grads are additionally zeroed.
        grad_np = np.asarray(grad)
        hess_np = np.asarray(hess)
        pad = rps * d - len(grad_np)
        if pad:
            grad_np = np.pad(grad_np, (0, pad))
            hess_np = np.pad(hess_np, (0, pad))
        counts0 = self._shard_valid_rows.copy()
        perm_np = np.broadcast_to(np.arange(rps, dtype=np.int32)[None],
                                  (d, rps)).copy()
        if num_data < self.dataset.num_data:
            mask = np.zeros(rps * d, dtype=bool)
            mask[np.asarray(perm[:num_data])] = True
            grad_np = np.where(mask, grad_np, 0.0)
            hess_np = np.where(mask, hess_np, 0.0)
            perm_np, counts0 = shard_bag_permutation(perm, num_data, d, rps)
        self._qscales = None
        raw_g_sh = raw_h_sh = None
        if self._quant:
            # one quantization pass per tree (bag-masked raw grads in,
            # int32 levels out); every sharded histogram and its psum
            # then run in exact level space, and the host keeps leaf
            # sums in dequantized f32 units
            Q.note_requantize(cfg.num_grad_quant_bins)
            key = jax.random.fold_in(
                jax.random.PRNGKey(cfg.objective_seed ^ 0x51A7),
                self._quant_tree_idx)
            self._quant_tree_idx += 1
            qg, qh, gs, hs = Q.quantize_gradients(
                jnp.asarray(grad_np), jnp.asarray(hess_np),
                cfg.num_grad_quant_bins, key, cfg.stochastic_rounding)
            self._qscales = (gs, hs)
            # tpulint: sync-ok(per-tree quant scales, single batched transfer)
            gsh, hsh = jax.device_get((gs, hs))
            self._qscales_host = (float(gsh), float(hsh))
            if cfg.quant_train_renew_leaf:
                raw_g_sh = jax.device_put(
                    jnp.asarray(grad_np.reshape(d, rps)), self._spec_rows)
                raw_h_sh = jax.device_put(
                    jnp.asarray(hess_np.reshape(d, rps)), self._spec_rows)
            g_sh = jax.device_put(qg.reshape(d, rps), self._spec_rows)
            h_sh = jax.device_put(qh.reshape(d, rps), self._spec_rows)
        else:
            g_sh = jax.device_put(jnp.asarray(grad_np.reshape(d, rps)), self._spec_rows)
            h_sh = jax.device_put(jnp.asarray(hess_np.reshape(d, rps)), self._spec_rows)
        perm_sh = jax.device_put(jnp.asarray(perm_np), self._spec_rows)

        tree = Tree(cfg.num_leaves,
                    track_branch_features=bool(self._interaction_sets))
        tree_mask = self._feature_mask_tree()
        rand_thr = self._rand_thresholds()

        starts0 = np.zeros(d, dtype=np.int32)
        cap = next_capacity(int(counts0.max()))
        hist, sg, sh = self._hist_call(
            cap, int(counts0.sum()),
            self.bins_sharded, perm_sh, jnp.asarray(starts0),
            jnp.asarray(counts0), g_sh, h_sh)
        # tpulint: sync-ok(per-tree root stats, single batched transfer)
        sg, sh = map(float, jax.device_get((sg, sh)))
        if self._qscales is not None:
            # int32 level sums -> dequantized f32 leaf totals
            sg *= self._qscales_host[0]
            sh *= self._qscales_host[1]
        root = _Leaf(starts0, counts0, sg, sh, 0.0, 0)
        root.hist = hist
        root.best = self._compute_best_dp(root, tree_mask,
                                          set() if self._interaction_sets else None,
                                          rand_thr)
        leaves: Dict[int, _Leaf] = {0: root}

        for _ in range(cfg.num_leaves - 1):
            best_leaf, best_gain = -1, 0.0
            for lid, leaf in leaves.items():
                if leaf.best is None:
                    continue
                if cfg.max_depth > 0 and leaf.depth >= cfg.max_depth:
                    continue
                if leaf.best["gain"] > best_gain:
                    best_leaf, best_gain = lid, leaf.best["gain"]
            if best_leaf < 0:
                break
            perm_sh = self._split_leaf_dp(tree, leaves, best_leaf, perm_sh,
                                          g_sh, h_sh, tree_mask, rand_thr)
        self.last_perm = perm_sh
        if self._quant and cfg.quant_train_renew_leaf:
            self._renew_leaf_values_dp(tree, leaves, perm_sh,
                                       raw_g_sh, raw_h_sh)
        return tree

    def _renew_leaf_values_dp(self, tree: Tree, leaves: Dict[int, _Leaf],
                              perm_sh, g_sh, h_sh) -> None:
        """Sharded mirror of SerialTreeGrower._renew_leaf_values: leaf
        outputs refit from the EXACT f32 grad/hess sums after quantized
        growth. One leaf-ordered cumsum per shard; only the [L, D]
        window-boundary prefix values transfer to the host, where the
        cross-shard sums and the output formula run in f64."""
        items = [(lid, lf) for lid, lf in leaves.items()
                 if int(np.sum(lf.count)) > 0]
        if not items:
            return
        cg = jnp.cumsum(jnp.take_along_axis(g_sh, perm_sh, axis=1), axis=1)
        ch = jnp.cumsum(jnp.take_along_axis(h_sh, perm_sh, axis=1), axis=1)
        starts = np.asarray([lf.start for _, lf in items])      # [L, D]
        counts = np.asarray([lf.count for _, lf in items])      # [L, D]
        ends = starts + counts - 1
        los = starts - 1
        dd = jnp.arange(self.num_shards, dtype=jnp.int32)[None, :]
        e_idx = jnp.asarray(np.maximum(ends, 0), jnp.int32)
        lo_idx = jnp.asarray(np.maximum(los, 0), jnp.int32)
        # tpulint: sync-ok(per-tree leaf renewal, already one batched transfer)
        ge, he, gl, hl = jax.device_get(
            (cg[dd, e_idx], ch[dd, e_idx], cg[dd, lo_idx], ch[dd, lo_idx]))
        has = counts > 0
        has_lo = los >= 0
        sum_g = np.sum(np.where(
            has, np.asarray(ge, np.float64) - np.where(has_lo, gl, 0.0),
            0.0), axis=1)
        sum_h = np.sum(np.where(
            has, np.asarray(he, np.float64) - np.where(has_lo, hl, 0.0),
            0.0), axis=1)
        cfg = self.config
        for (lid, lf), g, h in zip(items, sum_g, sum_h):
            if cfg.lambda_l1 > 0:
                g = np.sign(g) * max(abs(g) - cfg.lambda_l1, 0.0)
            out = -g / (h + cfg.lambda_l2 + S.K_EPSILON)
            if cfg.max_delta_step > 0:
                out = float(np.clip(out, -cfg.max_delta_step,
                                    cfg.max_delta_step))
            if self.use_monotone:
                out = float(np.clip(out, lf.cmin, lf.cmax))
            tree.leaf_value[lid] = float(out)

    def _compute_best_dp(self, leaf: _Leaf, tree_mask, branch_features,
                         rand_thr):
        total = int(np.sum(leaf.count))
        if total < 2 * self.config.min_data_in_leaf \
                or leaf.sum_h < 2 * self.config.min_sum_hessian_in_leaf:
            return None
        fake = _Leaf(0, total, leaf.sum_g, leaf.sum_h, leaf.output, leaf.depth,
                     hist=leaf.hist, cmin=leaf.cmin, cmax=leaf.cmax)
        return super()._compute_best(fake, tree_mask, branch_features, rand_thr)

    def _split_leaf_dp(self, tree: Tree, leaves: Dict[int, _Leaf], lid: int,
                       perm_sh, g_sh, h_sh, tree_mask, rand_thr):
        from ..io.binning import BIN_CATEGORICAL
        leaf = leaves[lid]
        best = leaf.best
        fi = best["feature"]
        mapper = self.dataset.bin_mappers[fi]
        real_feature = self.dataset.real_feature_index[fi]
        is_cat = mapper.bin_type == BIN_CATEGORICAL

        if is_cat:
            bin_set = self._cat_bins(best)
            bitset_bins = np.zeros((self.max_num_bin + 31) // 32, dtype=np.uint32)
            for b in bin_set:
                bitset_bins[b // 32] |= np.uint32(1 << (b % 32))
            cat_vals = sorted(mapper.bin_2_categorical[b] for b in bin_set
                              if mapper.bin_2_categorical[b] >= 0)
            right_leaf = tree.split_categorical(
                lid, fi, real_feature, sorted(bin_set), cat_vals,
                best["left_output"], best["right_output"],
                best["left_count"], best["right_count"],
                best["left_sum_hessian"], best["right_sum_hessian"],
                best["gain"], mapper.missing_type)
            cat_bitset_dev = jnp.asarray(bitset_bins)
            thr, dl, mb = 0, False, -1
        else:
            threshold_real = mapper.bin_to_value(best["threshold"])
            right_leaf = tree.split(
                lid, fi, real_feature, best["threshold"], threshold_real,
                best["left_output"], best["right_output"],
                best["left_count"], best["right_count"],
                best["left_sum_hessian"], best["right_sum_hessian"],
                best["gain"], mapper.missing_type, best["default_left"])
            cat_bitset_dev = jnp.zeros(1, jnp.uint32)
            thr, dl, mb = best["threshold"], best["default_left"], \
                int(self.feature_miss_bin[fi])

        cap = next_capacity(int(np.max(leaf.count)))
        new_perm, left_counts = self._partition_fn_sharded(cap)(
            self.bins_sharded, perm_sh, jnp.asarray(leaf.start),
            jnp.asarray(leaf.count), jnp.int32(fi), jnp.int32(thr),
            bool(dl), jnp.int32(mb), bool(is_cat), cat_bitset_dev)
        # tpulint: sync-ok(per-shard partition counts steer the host loop)
        lc = np.asarray(left_counts, dtype=np.int32)
        rc = leaf.count - lc

        lcmin, lcmax, rcmin, rcmax = leaf.cmin, leaf.cmax, leaf.cmin, leaf.cmax
        if self.use_monotone:
            mono = self.dataset.monotone_constraint(fi)
            if mono != 0:
                mid = (best["left_output"] + best["right_output"]) / 2.0
                if mono > 0:
                    lcmax, rcmin = min(lcmax, mid), max(rcmin, mid)
                else:
                    lcmin, rcmax = max(lcmin, mid), min(rcmax, mid)

        left = _Leaf(leaf.start.copy(), lc, best["left_sum_gradient"],
                     best["left_sum_hessian"], best["left_output"],
                     leaf.depth + 1, cmin=lcmin, cmax=lcmax)
        right = _Leaf(leaf.start + lc, rc, best["right_sum_gradient"],
                      best["right_sum_hessian"], best["right_output"],
                      leaf.depth + 1, cmin=rcmin, cmax=rcmax)

        lt, rt = int(lc.sum()), int(rc.sum())
        smaller, larger = (left, right) if lt <= rt else (right, left)
        scap = next_capacity(max(int(np.max(smaller.count)), 1))
        smaller.hist, _, _ = self._hist_call(
            scap, min(lt, rt),
            self.bins_sharded, new_perm, jnp.asarray(smaller.start),
            jnp.asarray(smaller.count), g_sh, h_sh)
        if self.supports_hist_subtraction:
            # exact in int32 level space under quantized training
            larger.hist = leaf.hist - smaller.hist
        else:
            # voting mode: each reduction round selects its own feature
            # subset, so parent/child histograms are not subtractable —
            # compute the larger child directly (its own vote round)
            lcap = next_capacity(max(int(np.max(larger.count)), 1))
            larger.hist, _, _ = self._hist_call(
                lcap, max(lt, rt),
                self.bins_sharded, new_perm, jnp.asarray(larger.start),
                jnp.asarray(larger.count), g_sh, h_sh)
        leaf.hist = None

        branches = None
        if self._interaction_sets:
            branches = {self.dataset.inner_feature_index[f]
                        for f in tree.branch_features[lid]
                        if f in self.dataset.inner_feature_index}
        left.best = self._compute_best_dp(left, tree_mask, branches, rand_thr)
        right.best = self._compute_best_dp(right, tree_mask, branches, rand_thr)
        leaves[lid] = left
        leaves[right_leaf] = right
        return new_perm


class VotingParallelTreeGrower(DataParallelTreeGrower):
    """PV-Tree voting (reference voting_parallel_tree_learner.cpp): each
    shard votes its local top-k features; only features with enough
    votes get their histograms globally reduced.

    With psum already reducing the full histogram in one ICI op, voting
    is expressed as a feature mask applied before the reduction: the
    local top-k is computed from shard-local scans, the vote tally is a
    psum of one-hot feature votes (tiny), and the big histogram psum is
    masked to the ≤2k selected features — the same traffic shape as
    CopyLocalHistogram (:185) + ReduceScatter (:343). Because each
    reduction round selects its own features, parent/child histograms
    are NOT subtractable (supports_hist_subtraction = False).
    """

    supports_hist_subtraction = False
    # the local vote scan evaluates real f32 gains, so the quantized
    # path must pass the per-tree scales into the sharded program
    _hist_takes_scales = True

    @functools.lru_cache(maxsize=64)
    def _hist_fn_sharded(self, capacity: int, packed: bool = False):
        B = self.max_num_bin
        Bg = self.group_max_bin
        efb_hist = self._efb_hist
        mesh = self.mesh
        top_k = self.config.top_k
        meta = self.meta
        cfg = self.split_cfg
        method = H.hist_method(self.config)
        quant = self._quant
        row_specs = (P("data", None, None), P("data", None), P("data"),
                     P("data"), P("data", None), P("data", None))
        in_specs = row_specs + ((P(), P()) if quant else ())

        def reduce_hist(h):
            # the big collective: packed [*, B] words (half bytes) when
            # the leaf's global count keeps 16-bit lane sums exact,
            # else the plain [*, B, 2] (f32, or int32 level) psum
            if packed:
                return Q.packed_hist_to_pairs(
                    jax.lax.psum(Q.pairs_to_packed_hist(h), "data"))
            return jax.lax.psum(h, "data")

        def body(bins, perm, start, count, grad, hess, gs=None, hs=None):
            h = H.leaf_histogram(bins[0], perm[0], start[0], count[0],
                                 grad[0], hess[0], capacity,
                                 Bg if efb_hist is not None else B,
                                 method=method)
            if efb_hist is not None:
                # voting scans LOCAL per-feature histograms; the mfb
                # reconstruction is linear in the group histogram, so
                # reconstructing per shard and psum'ing selected
                # features afterwards equals the global reconstruction
                from ..io.efb import per_feature_hist
                tot = h[0].sum(axis=0)
                h = per_feature_hist(h, efb_hist, tot[0], tot[1])
            # local scan for voting (min_data divided by #machines,
            # reference :62-64)
            local_cfg = S.SplitConfig(
                lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
                min_data_in_leaf=max(1, cfg.min_data_in_leaf // mesh.shape["data"]),
                min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf / mesh.shape["data"],
                min_gain_to_split=cfg.min_gain_to_split,
                max_delta_step=cfg.max_delta_step, path_smooth=cfg.path_smooth)
            sg = jnp.sum(h[0, :, 0])
            sh_ = jnp.sum(h[0, :, 1])
            if quant:
                # the vote scan runs on the dequantized LOCAL histogram
                # (gains are regularized, so level-space scans would
                # mix units); the collectives below stay integer
                h_scan = S.dequantize_hist(h, gs, hs)
                sg_scan = sg.astype(jnp.float32) * gs
                sh_scan = sh_.astype(jnp.float32) * hs
            else:
                h_scan, sg_scan, sh_scan = h, sg, sh_
            res = S.numerical_split_scan(h_scan, meta, local_cfg, sg_scan,
                                         sh_scan, count[0], 0.0,
                                         -jnp.inf, jnp.inf)
            gains = jnp.where(jnp.isfinite(res["gain"]), res["gain"], -jnp.inf)
            f_total = gains.shape[0]
            k = min(top_k, f_total)
            _, top_idx = jax.lax.top_k(gains, k)
            votes = jnp.zeros(f_total, jnp.int32).at[top_idx].add(1)
            votes = jax.lax.psum(votes, "data")        # tiny: [F] int32
            # global candidates: top 2k features by votes (GlobalVoting,
            # reference :152-183)
            k2 = min(2 * top_k, f_total)
            sg_true = jax.lax.psum(sg, "data")
            sh_true = jax.lax.psum(sh_, "data")
            if k2 >= f_total:
                return reduce_hist(h), sg_true, sh_true
            # the vote tally is replicated after its psum, so every
            # shard computes the SAME selected set; only the selected
            # features' histogram slab rides ICI — [2k, B, 2] instead of
            # [F, B, 2], the PV-Tree saving (CopyLocalHistogram :185 +
            # ReduceScatter of selected buffers :343)
            _, selected = jax.lax.top_k(votes, k2)
            h_sel = reduce_hist(h[selected])           # [2k, B, 2]
            hist_global = jnp.zeros_like(h).at[selected].set(h_sel)
            # non-selected features keep zero histograms; the replicated
            # scan will simply not pick them
            return hist_global, sg_true, sh_true

        if quant:
            def fn_args(bins, perm, start, count, grad, hess, gs, hs):
                return body(bins, perm, start, count, grad, hess, gs, hs)
        else:
            def fn_args(bins, perm, start, count, grad, hess):
                return body(bins, perm, start, count, grad, hess)
        fn = jax.jit(functools.partial(
            shard_map, mesh=mesh, check_vma=False,
            in_specs=in_specs, out_specs=P())(fn_args))
        # ICI traffic per call: the [F] vote tally + the selected
        # [<=2k, B, 2] histogram slab (full [F, B, 2] when 2k >= F;
        # halved when packed)
        k2_est = min(2 * top_k, self.num_features)
        from ..compile import get_manager
        return instrument_kernel(
            get_manager().jit_entry(
                f"voting_parallel/leaf_histogram_c{capacity}"
                + ("_packed" if packed else ""), fn),
            "hist", name="voting_parallel/leaf_histogram",
            collective=("voting_psum",
                        self.num_features * 4
                        + k2_est * B * (1 if packed else 2) * 4,
                        "data"))


class FeatureParallelTreeGrower(SerialTreeGrower):
    """Feature-sharded learner (reference
    feature_parallel_tree_learner.cpp): every chip holds all rows; each
    evaluates splits for its feature shard; best split = argmax over the
    feature axis — realized by sharding the histogram scan over the mesh
    with jit-with-sharding (XLA inserts the tiny allreduce-max for the
    final argmax; no histogram traffic at all, like the reference which
    only syncs SplitInfo)."""

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        super().__init__(dataset, config)
        self.mesh = mesh if mesh is not None else build_mesh(config)
        # shard the histogram scan over features: hist [F, B, 2] with F
        # sharded. The per-feature scans are independent, so simply
        # constraining the sharding of the hist input distributes the
        # scan; everything else (gather, partition) is replicated.
        self._hist_sharding = NamedSharding(self.mesh, P("data", None, None))

    def _split_packed(self, hist, *args):
        hist = jax.lax.with_sharding_constraint(hist, self._hist_sharding)
        return super()._split_packed(hist, *args)


class FusedDataParallelGrower(FusedSerialGrower):
    """Fused single-dispatch iterations under `shard_map` — the
    data-parallel learner for the persistent training path.

    Reference analogue: data_parallel_tree_learner.cpp, but instead of
    a ReduceScatter of histogram buffers per LEAF over sockets
    (:169), the whole `lax.while_loop` tree build runs per shard with
    one `psum` of the smaller child's histogram (and of the split
    counts) per split riding ICI. Rows are sharded contiguously over
    the 1-D "data" mesh axis; each shard partitions only its own rows
    and carries its own leaf windows, while split decisions are made
    on the psum'd (global) histograms — bitwise identical on every
    shard, so the resulting tree is replicated by construction (the
    reference's SyncUpGlobalBestSplit, :240, becomes a no-op).
    """

    is_multichip = True

    def __init__(self, dataset: BinnedDataset, config: Config,
                 objective=None, mesh: Optional[Mesh] = None) -> None:
        self.mesh = mesh if mesh is not None else build_mesh(config)
        self.num_shards = int(self.mesh.shape["data"])
        self.global_rows = dataset.num_data
        shard_rows = -(-dataset.num_data // self.num_shards)
        super().__init__(dataset, config, objective,
                         num_rows_override=shard_rows)
        self.shard_rows = shard_rows
        self.psum_axis = "data"
        n = self.global_rows
        counts = [max(0, min(n - d * shard_rows, shard_rows))
                  for d in range(self.num_shards)]
        self._n_per_shard = jax.device_put(
            jnp.asarray(counts, jnp.int32),
            NamedSharding(self.mesh, P("data")))
        self._iter_mc_jit = None
        self._grow_mc_tree_jit = None
        # per-tree ICI estimate: one [F, B, 2] f32 child-histogram psum
        # per split, num_leaves - 1 splits per tree
        self._tree_psum_bytes = ((config.num_leaves - 1)
                                 * self.num_features * self.max_num_bin
                                 * 2 * 4)

    def _mc_signature(self, extra: Optional[dict] = None):
        """(sig, shareable) for the top-level shard_map entries. The
        per-shard fused grower skips manager registration (its programs
        mutate post-init), but THESE entries are built after that
        mutation settles, so two MC growers with equal signatures trace
        identical sharded programs and can share one executable. The
        bodies close over dataset-derived tables, so the dataset trace
        signature joins the fused compile signature, as on the serial
        path."""
        ds_sig, shareable = self.dataset.trace_signature()
        sig = self._compile_signature()
        sig["ds"] = ds_sig
        sig["mesh"] = (self.num_shards, self.shard_rows, self.global_rows)
        if extra:
            sig.update(extra)
        return sig, shareable

    # -- sharded state construction ------------------------------------
    def _shard_lane_pad(self, v, fill=0.0, dtype=jnp.float32):
        """[n] global -> [D * num_lanes] with per-shard lane padding."""
        D, sr, Ly = self.num_shards, self.shard_rows, self.layout
        v = jnp.asarray(v, dtype)
        v = jnp.pad(v, (0, D * sr - v.shape[0]), constant_values=fill)
        v = v.reshape(D, sr)
        v = jnp.pad(v, ((0, 0), (0, Ly.num_lanes - sr)),
                    constant_values=fill)
        return v.reshape(-1)

    def init_persistent_state(self, score_vec) -> jax.Array:
        assert self.persistent_capable
        from ..ops import plane
        D, sr, Ly = self.num_shards, self.shard_rows, self.layout
        aux_label, aux_weight = self.objective.persistent_aux()
        n = self.global_rows
        # host-side pad: reading the lazy `self.bins` property would
        # upload + CACHE the full global row-major matrix on one device
        # (the HBM waste the lazy property exists to avoid)
        bins_pad = np.pad(np.asarray(self.dataset.bins),
                          ((0, D * sr - n), (0, 0)))
        shards = []
        for d in range(D):
            cp = plane.build_codes_planes(
                bins_pad[d * sr:(d + 1) * sr], Ly)
            rowid = jnp.arange(d * sr, (d + 1) * sr, dtype=jnp.int32)
            # pad rows alias row id n -> dropped by the sync scatter
            rowid = jnp.where(rowid < n, rowid, n)
            rowid = jnp.pad(rowid, (0, Ly.num_lanes - sr),
                            constant_values=n)
            zero = jnp.zeros(Ly.num_lanes, jnp.float32)
            shards.append(plane.build_data(
                Ly, cp, zero, zero, rowid=rowid))
        data = jnp.concatenate(shards, axis=1)
        lab = self._shard_lane_pad(aux_label)
        sc = self._shard_lane_pad(jnp.asarray(score_vec, jnp.float32))
        data = data.at[Ly.label].set(plane.f32_as_i32(lab))
        data = data.at[Ly.score].set(plane.f32_as_i32(sc))
        if Ly.weight >= 0:
            data = data.at[Ly.weight].set(
                plane.f32_as_i32(self._shard_lane_pad(aux_weight)))
        return jax.device_put(
            data, NamedSharding(self.mesh, P(None, "data")))

    # -- sharded iteration ---------------------------------------------
    # NOTE on quantized training: the in-graph per-split child-histogram
    # psum stays at the unpacked [F, B, 2] int32 width — leaf counts are
    # TRACED inside the while_loop, so the packed/unpacked choice cannot
    # branch per leaf the way the host-loop learner's _hist_call does.
    # The quantization scales pmax across shards before packing (see
    # FusedSerialGrower._train_iter), so the int32 sums stay coherent.
    def train_iter_persistent(self, data, shrinkage, bias, mask=None):
        if mask is None:
            mask = self.feature_masks_for_tree()
        quant = self._quant
        if self._iter_mc_jit is None:
            if quant:
                def body(data_l, nvalid_l, mask_, shr, b, key):
                    return self._train_iter(data_l, mask_, shr, b,
                                            n_valid=nvalid_l[0], key=key)
                in_specs = (P(None, "data"), P("data"), P(), P(), P(), P())
            else:
                def body(data_l, nvalid_l, mask_, shr, b):
                    return self._train_iter(data_l, mask_, shr, b,
                                            n_valid=nvalid_l[0])
                in_specs = (P(None, "data"), P("data"), P(), P(), P())
            f = functools.partial(
                shard_map, mesh=self.mesh, check_vma=False,
                in_specs=in_specs,
                out_specs=(P(None, "data"), P()))(body)
            from ..compile import get_manager
            sig, ok = self._mc_signature()
            self._iter_mc_jit = get_manager().shared_entry(
                "mc/train_iter", sig,
                lambda: jax.jit(f, donate_argnums=0),  # tpulint: jit-ok(inside a shared_entry builder; the manager dispatches this jit)
                donate_argnums=(0,), store=ok)
        args = (data, self._n_per_shard, mask, jnp.float32(shrinkage),
                jnp.float32(bias))
        if quant:
            args = args + (self._next_quant_keys(1)[0],)
        with collective_span("fused_iter_psum", self._tree_psum_bytes,
                             axis="data"):
            return self._iter_mc_jit(*args)

    def train_iters_persistent(self, data, shrinkage, masks):
        """K sharded iterations in one dispatch (scan inside shard_map);
        see FusedSerialGrower.train_iters_persistent."""
        k = int(masks.shape[0])
        quant = self._quant
        if getattr(self, "_iters_mc_jit_k", None) is None:
            self._iters_mc_jit_k = {}
        if k not in self._iters_mc_jit_k:
            if quant:
                def body(data_l, nvalid_l, masks_, shr, keys):
                    def step(d, xs):
                        mask, key = xs
                        d, ta = self._train_iter(d, mask, shr,
                                                 jnp.float32(0.0),
                                                 n_valid=nvalid_l[0],
                                                 key=key)
                        return d, ta
                    return jax.lax.scan(step, data_l, (masks_, keys),
                                        length=k)
                in_specs = (P(None, "data"), P("data"), P(), P(), P())
            else:
                def body(data_l, nvalid_l, masks_, shr):
                    def step(d, mask):
                        d, ta = self._train_iter(d, mask, shr,
                                                 jnp.float32(0.0),
                                                 n_valid=nvalid_l[0])
                        return d, ta
                    return jax.lax.scan(step, data_l, masks_, length=k)
                in_specs = (P(None, "data"), P("data"), P(), P())
            f = functools.partial(
                shard_map, mesh=self.mesh, check_vma=False,
                in_specs=in_specs,
                out_specs=(P(None, "data"), P()))(body)
            from ..compile import get_manager
            sig, ok = self._mc_signature({"k": k})
            self._iters_mc_jit_k[k] = get_manager().shared_entry(
                f"mc/train_iters_k{k}", sig,
                lambda: jax.jit(f, donate_argnums=0),  # tpulint: jit-ok(inside a shared_entry builder; the manager dispatches this jit)
                donate_argnums=(0,), store=ok)
        args = (data, self._n_per_shard, masks, jnp.float32(shrinkage))
        if quant:
            args = args + (self._next_quant_keys(k),)
        with collective_span("fused_iter_psum",
                             k * self._tree_psum_bytes, axis="data"):
            return self._iters_mc_jit_k[k](*args)

    def _sync_scores(self, data):
        from ..ops import plane
        Ly = self.layout
        n = self.global_rows

        def body(data_l):
            rowids = data_l[Ly.rowid]
            score = plane.get_f32(data_l, Ly.score)
            out = jnp.zeros(n, jnp.float32).at[rowids].set(
                score, mode="drop", unique_indices=True)
            return jax.lax.psum(out, "data")

        with collective_span("scores_psum", n * 4, axis="data"):
            return functools.partial(
                shard_map, mesh=self.mesh, check_vma=False,
                in_specs=(P(None, "data"),), out_specs=P())(body)(data)

    # -- sharded per-tree path (bagging / multiclass / custom fobj) -----
    def _bins_row_sharded(self):
        """[D, sr, F] row-contiguous bin shards (same ownership as the
        persistent state: shard d owns rows [d*sr, (d+1)*sr))."""
        if getattr(self, "_bins_sh", None) is None:
            D, sr = self.num_shards, self.shard_rows
            bins_np = np.asarray(self.dataset.bins)
            pad = D * sr - bins_np.shape[0]
            if pad:
                bins_np = np.pad(bins_np, ((0, pad), (0, 0)), mode="edge")
            self._bins_sh = jax.device_put(
                jnp.asarray(bins_np.reshape(D, sr, -1)),
                NamedSharding(self.mesh, P("data", None, None)))
        return self._bins_sh

    def _sharded_bag_views(self, perm, bag_cnt):
        """Device-resident (per-shard local perms, per-shard counts) for
        a bag. Cached on the perm object so the k class trees of one
        iteration (and consecutive no-bagging iterations) skip the O(n)
        host pass and the [n]-sized upload entirely."""
        key = (id(perm), int(bag_cnt))
        if getattr(self, "_bag_cache_key", None) == key:
            return self._bag_cache_val
        D, sr, n = self.num_shards, self.shard_rows, self.global_rows
        spec_rows = NamedSharding(self.mesh, P("data", None))
        if bag_cnt >= n:
            # no bagging: identity local perms, true per-shard row counts
            perm_np = np.broadcast_to(
                np.arange(sr, dtype=np.int32)[None], (D, sr))
            counts = np.asarray(
                [max(0, min(n - d * sr, sr)) for d in range(D)], np.int32)
        else:
            perm_np, counts = shard_bag_permutation(perm, bag_cnt, D, sr)
        val = (jax.device_put(jnp.asarray(perm_np), spec_rows),
               jax.device_put(jnp.asarray(counts),
                              NamedSharding(self.mesh, P("data"))))
        self._bag_cache_key = key
        self._bag_cache_ref = perm      # keep id() stable
        self._bag_cache_val = val
        return val

    def _grow_mc_jit_build(self):
        from ..ops import plane
        Ly = self.layout

        def body(bins_l, perm_l, cnt_l, g_l, h_l, mask):
            bins_l, perm_l, cnt_l = bins_l[0], perm_l[0], cnt_l[0]
            g_l, h_l = g_l[0], h_l[0]
            # one row gather per TREE (not per split) builds the
            # bag-ordered planar pack, as on the single-chip path
            cp = plane.build_codes_planes(bins_l[perm_l], Ly)
            data = plane.build_data(Ly, cp, g_l[perm_l], h_l[perm_l],
                                    rowid=perm_l)
            ta, _st = self._grow_tree_core(data, cnt_l, mask)
            # leaf of EVERY local row (incl. out-of-bag) for the score
            # update, via bin-space traversal of the fresh tree
            leaf = self.traverse_bins(ta, bins_l)
            return ta, leaf[None]

        f = functools.partial(
            shard_map, mesh=self.mesh, check_vma=False,
            in_specs=(P("data", None, None), P("data", None), P("data"),
                      P("data", None), P("data", None), P()),
            out_specs=(P(), P("data", None)))(body)
        from ..compile import get_manager
        sig, ok = self._mc_signature()
        return get_manager().shared_entry(
            "mc/grow_tree", sig,
            lambda: jax.jit(f),  # tpulint: jit-ok(inside a shared_entry builder; the manager dispatches this jit)
            store=ok)

    def grow_device(self, grad, hess, perm, bag_cnt,
                    compute_score_update=True):
        """Sharded per-tree growth (reference
        data_parallel_tree_learner.cpp covers every config through one
        network layer; here every config runs the same while_loop
        program per shard with psum'd histograms)."""
        D, sr, n = self.num_shards, self.shard_rows, self.global_rows
        perm_dev, counts_dev = self._sharded_bag_views(perm, bag_cnt)
        spec_rows = NamedSharding(self.mesh, P("data", None))

        def pad_rows(v):
            v = jnp.asarray(v, jnp.float32)
            v = jnp.pad(v, (0, D * sr - v.shape[0]))
            return jax.device_put(v.reshape(D, sr), spec_rows)

        if self._grow_mc_tree_jit is None:
            self._grow_mc_tree_jit = self._grow_mc_jit_build()
        with collective_span("fused_tree_psum", self._tree_psum_bytes,
                             axis="data"):
            ta, leaf = self._grow_mc_tree_jit(
                self._bins_row_sharded(), perm_dev, counts_dev,
                pad_rows(grad), pad_rows(hess),
                self.feature_masks_for_tree())
        leaf_of_row = leaf.reshape(-1)[:n] if compute_score_update else None
        return ta, leaf_of_row



def create_parallel_learner(kind: str, dataset: BinnedDataset,
                            config: Config, mesh: Optional[Mesh] = None):
    """reference TreeLearner::CreateTreeLearner (tree_learner.h:99)."""
    if kind == "data":
        return DataParallelTreeGrower(dataset, config, mesh)
    if kind == "voting":
        return VotingParallelTreeGrower(dataset, config, mesh)
    if kind == "feature":
        return FeatureParallelTreeGrower(dataset, config, mesh)
    log.fatal("Unknown parallel tree learner %s", kind)
