"""tpulint core: package model, pragmas, call graph, findings.

The analyzer is a plain-`ast` static pass over the package's own
sources — no imports of the analyzed code, no jax dependency — so it
runs in milliseconds and can't be confused by import-time side effects.

Model
-----
- `SourceFile`: one parsed module + its `# tpulint:` pragma lines.
- `FunctionInfo`: every function/method, keyed by a stable qualname
  `<relpath>::<Class.>name` (nested functions append `.name`).
- `Package`: the file set, a symbol index, per-module import aliases,
  and a name-resolved call graph with a simple-name fallback for
  `obj.method(...)` calls whose receiver type is unknown. The fallback
  OVER-approximates reachability on purpose: a sync point wrongly
  classified hot is a pragma away from quiet, one wrongly classified
  setup is a silent regression.

Pragmas
-------
`# tpulint: <kind>(<reason>)` on the offending line, or alone on the
line directly above it. Kinds: `sync-ok`, `jit-ok`, `trace-ok`,
`lock-ok`, `switch-ok`, the meshlint kinds `mesh-ok`, `tile-ok`,
`dtype-ok`, plus the lifelint kinds `donate-ok`, `thread-ok`.
The reason is mandatory — a bare pragma is itself a finding.

Findings & baseline
-------------------
A `Finding` is keyed WITHOUT its line number (rule, file, function,
site code), so pure line drift doesn't churn the baseline. The baseline
maps key -> allowed count; a new occurrence of an already-baselined
site kind in the same function still fails once it exceeds the count.
Workflow: the baseline only ever shrinks (docs/STATIC_ANALYSIS.md).
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

PRAGMA_RE = re.compile(r"#\s*tpulint:\s*([a-z-]+)\s*(?:\(\s*([^)]*?)\s*\))?")
PRAGMA_KINDS = ("sync-ok", "jit-ok", "trace-ok", "lock-ok",
                "switch-ok", "mesh-ok", "tile-ok", "dtype-ok",
                "donate-ok", "thread-ok")

# numpy / jax module spellings recognized as import roots
_NUMPY_MODULES = ("numpy",)
_JNP_MODULES = ("jax.numpy",)
_JAX_MODULES = ("jax",)


@dataclasses.dataclass(frozen=True)
class Pragma:
    kind: str
    reason: str
    line: int


@dataclasses.dataclass
class Finding:
    rule: str          # "trace-safety" | "sync-point" | "recompile-hazard" | "lock-discipline"
    path: str          # repo-relative file path
    line: int
    func: str          # qualname of the enclosing function ("" = module level)
    code: str          # short stable site descriptor, e.g. "np.asarray"
    message: str

    @property
    def key(self) -> str:
        """Line-independent baseline key."""
        return f"{self.rule}|{self.path}|{self.func}|{self.code}"

    def __str__(self) -> str:
        where = f"{self.path}:{self.line}"
        fn = f" [{self.func}]" if self.func else ""
        return f"{where}: {self.rule}: {self.message}{fn}"


class SourceFile:
    def __init__(self, rel: str, text: str) -> None:
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        self.pragmas: Dict[int, List[Pragma]] = {}
        for i, line in enumerate(self.lines, start=1):
            if "tpulint" not in line:
                continue
            for m in PRAGMA_RE.finditer(line):
                self.pragmas.setdefault(i, []).append(
                    Pragma(m.group(1), (m.group(2) or "").strip(), i))

    def pragma_at(self, line: int, kind: str) -> Optional[Pragma]:
        """Pragma of `kind` covering `line`: same line, or alone on the
        line above (a standalone-comment pragma)."""
        for p in self.pragmas.get(line, ()):
            if p.kind == kind:
                return p
        above = line - 1
        if above in self.pragmas and above <= len(self.lines):
            src = self.lines[above - 1].strip()
            if src.startswith("#"):
                for p in self.pragmas[above]:
                    if p.kind == kind:
                        return p
        return None


@dataclasses.dataclass
class FunctionInfo:
    qual: str                       # "<rel>::<Class.>name"
    rel: str
    cls: Optional[str]
    name: str
    node: ast.AST                   # FunctionDef | AsyncFunctionDef | Lambda
    params: List[str]
    lineno: int


def _func_params(node: ast.AST) -> List[str]:
    a = node.args
    params = [p.arg for p in getattr(a, "posonlyargs", [])] + \
        [p.arg for p in a.args]
    if a.vararg:
        params.append(a.vararg.arg)
    params += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        params.append(a.kwarg.arg)
    return params


class _FunctionCollector(ast.NodeVisitor):
    def __init__(self, rel: str) -> None:
        self.rel = rel
        self.funcs: List[FunctionInfo] = []
        self.class_bases: Dict[str, List[str]] = {}
        self._cls: List[str] = []
        self._fn: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        self.class_bases[node.name] = bases
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def _visit_func(self, node) -> None:
        name = ".".join(self._fn + [node.name])
        cls = self._cls[-1] if self._cls else None
        qual = f"{self.rel}::{cls + '.' if cls else ''}{name}"
        self.funcs.append(FunctionInfo(
            qual, self.rel, cls, name, node, _func_params(node), node.lineno))
        self._fn.append(node.name)
        self.generic_visit(node)
        self._fn.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


class ModuleImports:
    """Import aliases of one module, resolved against the package."""

    def __init__(self, rel: str, tree: ast.Module, pkg_rels: Set[str],
                 pkg_name: str) -> None:
        self.numpy: Set[str] = set()
        self.jnp: Set[str] = set()
        self.jax: Set[str] = set()
        # alias -> package-relative module path ("ops/histogram.py")
        self.modules: Dict[str, str] = {}
        # imported symbol -> (module rel, symbol name)
        self.symbols: Dict[str, Tuple[str, str]] = {}
        base_dir = os.path.dirname(rel)

        def rel_of(module: Optional[str], level: int) -> Optional[str]:
            if level == 0:
                if module and (module == pkg_name
                               or module.startswith(pkg_name + ".")):
                    parts = module.split(".")[1:]
                else:
                    return None
            else:
                d = base_dir
                for _ in range(level - 1):
                    d = os.path.dirname(d)
                parts = ([p for p in d.split(os.sep) if p]
                         + (module.split(".") if module else []))
            cand = os.path.join(*parts) + ".py" if parts else None
            if cand and cand in pkg_rels:
                return cand
            cand = os.path.join(*(parts + ["__init__.py"])) if parts else None
            return cand if cand in pkg_rels else None

        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    asname = al.asname or al.name.split(".")[0]
                    if al.name in _NUMPY_MODULES:
                        self.numpy.add(al.asname or al.name)
                    elif al.name in _JNP_MODULES and al.asname:
                        self.jnp.add(al.asname)
                    elif al.name in _JAX_MODULES:
                        self.jax.add(al.asname or al.name)
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0 and node.module == "jax" :
                    for al in node.names:
                        if al.name == "numpy":
                            self.jnp.add(al.asname or al.name)
                    continue
                mod_rel = rel_of(node.module, node.level)
                for al in node.names:
                    asname = al.asname or al.name
                    if mod_rel is None:
                        # maybe importing a submodule: from ..ops import histogram
                        sub = rel_of((node.module + "." if node.module else "")
                                     + al.name, node.level)
                        if sub is not None:
                            self.modules[asname] = sub
                        continue
                    sub = rel_of((node.module + "." if node.module else "")
                                 + al.name, node.level)
                    if sub is not None:
                        self.modules[asname] = sub
                    else:
                        self.symbols[asname] = (mod_rel, al.name)


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a pure Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Package:
    """The analyzed file set plus derived indices."""

    def __init__(self, root: str, rels: Sequence[str],
                 pkg_name: str = "lightgbm_tpu") -> None:
        self.root = root
        self.pkg_name = pkg_name
        self.files: Dict[str, SourceFile] = {}
        for rel in rels:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                self.files[rel] = SourceFile(rel, fh.read())
        rel_set = set(self.files)
        self.imports: Dict[str, ModuleImports] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.class_bases: Dict[str, Dict[str, List[str]]] = {}
        # simple name -> quals (for receiver-unknown method calls)
        self.by_name: Dict[str, List[str]] = {}
        for rel, sf in self.files.items():
            self.imports[rel] = ModuleImports(rel, sf.tree, rel_set, pkg_name)
            col = _FunctionCollector(rel)
            col.visit(sf.tree)
            self.class_bases[rel] = col.class_bases
            for fi in col.funcs:
                self.functions[fi.qual] = fi
                self.by_name.setdefault(fi.name.split(".")[-1], []).append(
                    fi.qual)
        self._call_graph: Optional[Dict[str, Set[str]]] = None

    @classmethod
    def load(cls, root: Optional[str] = None,
             subdir: str = "lightgbm_tpu") -> "Package":
        """Package rooted at the repo checkout (default: the parent of
        this package's own directory)."""
        if root is None:
            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        rels = []
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, subdir)):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for f in sorted(filenames):
                if f.endswith(".py"):
                    rels.append(os.path.relpath(os.path.join(dirpath, f),
                                                root))
        return cls(root, rels)

    # -- resolution -----------------------------------------------------
    def _method_in_class(self, rel: str, cls: str, name: str
                         ) -> Optional[str]:
        """Resolve Class.name in `rel`, walking base classes by name
        (package-wide for bases imported from another module)."""
        seen: Set[Tuple[str, str]] = set()
        stack = [(rel, cls)]
        while stack:
            r, c = stack.pop()
            if (r, c) in seen:
                continue
            seen.add((r, c))
            q = f"{r}::{c}.{name}"
            if q in self.functions:
                return q
            for base in self.class_bases.get(r, {}).get(c, ()):
                if base in self.class_bases.get(r, {}):
                    stack.append((r, base))
                else:
                    imp = self.imports[r].symbols.get(base)
                    if imp is not None:
                        stack.append((imp[0], imp[1]))
                    else:
                        for r2, classes in self.class_bases.items():
                            if base in classes:
                                stack.append((r2, base))
        return None

    def resolve_call(self, rel: str, caller: Optional[FunctionInfo],
                     func_expr: ast.AST, fallback: bool = True) -> Set[str]:
        """Possible callee qualnames for one Call.func expression.
        Empty set = external / unresolvable.

        `fallback=False` disables the unknown-receiver simple-name
        matching: only confident resolutions (self methods, module
        aliases, imported symbols) are returned. Reachability analyses
        want the over-approximation; taint analyses don't — `s.add(x)`
        on a set must not taint every function named `add`."""
        imps = self.imports[rel]
        out: Set[str] = set()
        if isinstance(func_expr, ast.Name):
            name = func_expr.id
            if name in imps.symbols:
                mod, sym = imps.symbols[name]
                q = f"{mod}::{sym}"
                if q in self.functions:
                    return {q}
                # imported class: constructor
                q = f"{mod}::{sym}.__init__"
                if q in self.functions:
                    return {q}
                return set()
            q = f"{rel}::{name}"
            if q in self.functions:
                return {q}
            if name in self.class_bases.get(rel, {}):
                q = f"{rel}::{name}.__init__"
                return {q} if q in self.functions else set()
            # nested function visible from the caller's scope: its own
            # children first, then each enclosing function scope (a
            # sibling closure like `body` next to a shard_map-wrapped
            # `fn_args`). Stops above the outermost function — a bare
            # name can't reach class scope.
            if caller is not None:
                path = caller.qual.split("::", 1)[1].split(".")
                floor = 1 if caller.cls else 0
                for i in range(len(path), floor, -1):
                    q = f"{rel}::{'.'.join(path[:i] + [name])}"
                    if q in self.functions:
                        return {q}
            return set()
        if isinstance(func_expr, ast.Attribute):
            attr = func_expr.attr
            base = func_expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and caller is not None and caller.cls:
                    q = self._method_in_class(rel, caller.cls, attr)
                    if q is not None:
                        return {q}
                    return set(self.by_name.get(attr, ())) if fallback \
                        else set()
                if base.id in imps.modules:
                    q = f"{imps.modules[base.id]}::{attr}"
                    if q in self.functions:
                        return {q}
                    return set()
                if base.id in (imps.numpy | imps.jnp | imps.jax):
                    return set()
            if isinstance(base, ast.Call) and isinstance(base.func, ast.Name) \
                    and base.func.id == "super" and caller is not None \
                    and caller.cls:
                for b in self.class_bases.get(rel, {}).get(caller.cls, ()):
                    q = self._method_in_class(rel, b, attr)
                    if q is not None:
                        out.add(q)
                return out
            md = dotted(func_expr)
            if md is not None:
                root = md.split(".")[0]
                if root in (imps.numpy | imps.jnp | imps.jax):
                    return set()
            # unknown receiver: fall back to simple-name matching
            return set(self.by_name.get(attr, ())) if fallback else set()
        return out

    # -- call graph -----------------------------------------------------
    def call_graph(self) -> Dict[str, Set[str]]:
        if self._call_graph is not None:
            return self._call_graph
        graph: Dict[str, Set[str]] = {}
        for qual, fi in self.functions.items():
            edges: Set[str] = set()
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    edges |= self.resolve_call(fi.rel, fi, node.func)
            graph[qual] = edges
        self._call_graph = graph
        return graph

    def reachable(self, roots: Iterable[str]) -> Set[str]:
        graph = self.call_graph()
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(graph.get(q, ()) - seen)
        return seen

    def enclosing_function(self, rel: str, node: ast.AST
                           ) -> Optional[FunctionInfo]:
        best: Optional[FunctionInfo] = None
        for fi in self.functions.values():
            if fi.rel != rel:
                continue
            end = getattr(fi.node, "end_lineno", fi.lineno)
            if fi.lineno <= node.lineno <= end:
                if best is None or fi.lineno >= best.lineno:
                    best = fi
        return best


# -- baseline ------------------------------------------------------------
BASELINE_VERSION = 1


def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def save_baseline(path: str, findings: Sequence[Finding]) -> Dict[str, int]:
    entries: Dict[str, int] = {}
    for f in findings:
        entries[f.key] = entries.get(f.key, 0) + 1
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BASELINE_VERSION,
                   "entries": {k: entries[k] for k in sorted(entries)}},
                  fh, indent=1, sort_keys=False)
        fh.write("\n")
    return entries


def apply_baseline(findings: Sequence[Finding], baseline: Dict[str, int]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined): each baseline key absorbs up to its count."""
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
