"""CLI: `python -m lightgbm_tpu.analysis`.

Exit status 0 iff every finding is absorbed by the baseline. Typical
use:

    python -m lightgbm_tpu.analysis                 # lint the repo
    python -m lightgbm_tpu.analysis --format json   # machine-readable
    python -m lightgbm_tpu.analysis --rules sync-point,lock-discipline
    python -m lightgbm_tpu.analysis --write-baseline  # re-audit ONLY:
        # regenerates baseline.json from current findings. The baseline
        # workflow is shrink-only — see docs/STATIC_ANALYSIS.md.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import (DEFAULT_BASELINE, Package, RULE_PACKS, collect, run,
               save_baseline, summary)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="tpulint: JAX-aware static analysis for lightgbm_tpu")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: this checkout)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current findings "
                         "(re-audit only; the baseline never grows in "
                         "normal workflow)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset: "
                         + ",".join(RULE_PACKS) + ",pragma")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--json", action="store_true",
                    help="shorthand for --format json (CI artifacts)")
    args = ap.parse_args(argv)
    if args.json:
        args.format = "json"

    rules = args.rules.split(",") if args.rules else None
    if args.write_baseline:
        pkg = Package.load(args.root)
        findings = collect(pkg, rules)
        entries = save_baseline(args.baseline, findings)
        print(f"wrote {args.baseline}: {sum(entries.values())} occurrences "
              f"across {len(entries)} keys")
        return 0

    result = run(args.root, "" if args.no_baseline else args.baseline,
                 rules)
    if args.no_baseline:
        result.new.extend(result.baselined)
        result.baselined = []
        result.new.sort(key=lambda f: (f.path, f.line, f.rule, f.code))

    if args.format == "json":
        # by_pack: every ENABLED pack with its new-finding count, zero
        # included — the CI artifact must show a pack ran and was
        # clean, not merely omit it
        enabled = [r for r in RULE_PACKS if rules is None or r in rules]
        by_rule = summary(result)
        print(json.dumps({
            "ok": result.ok,
            "by_rule": by_rule,
            "by_pack": {r: by_rule.get(r, 0) for r in enabled},
            "new": [{**vars(f), "location": f"{f.path}:{f.line}"}
                    for f in result.new],
            "baselined": len(result.baselined),
            "baseline_size": result.baseline_size,
            "hot_sync_count": result.hot_sync_count,
        }, indent=1))
    else:
        for f in result.new:
            print(str(f))
        by_rule = summary(result)
        tail = ("  [" + ", ".join(f"{k}: {v}" for k, v in
                                  sorted(by_rule.items())) + "]"
                if by_rule else "")
        print(f"tpulint: {len(result.new)} new finding(s){tail}, "
              f"{len(result.baselined)} baselined "
              f"(baseline budget {result.baseline_size}), "
              f"{result.hot_sync_count} hot-loop sync site(s)")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
