"""Rule pack: sync-point budget.

Builds an inventory of every host<->device synchronization site in the
package — explicit (`jax.device_get`, `.block_until_ready()`) and
implicit (`.item()` / `.tolist()`, `np.asarray`/`np.array` on a device
value, `float()`/`int()`/`bool()` on a device value) — and classifies
each as **hot-loop** (reachable from the per-iteration training roots)
or **setup**.

Hot roots: `GBDT.train_one_iter` / `GBDT.eval_at_iter` (plus subclass
overrides) and `engine._telemetry_end_iteration`. Reachability uses the
package call graph, whose unknown-receiver fallback deliberately
over-approximates: a sync wrongly marked hot costs one pragma, one
wrongly marked setup is a silent per-iteration regression.

"Device value" is a local, per-function heuristic: results of
`jnp.*` / `jax.*` calls, of calls through a `*_jit`/`*_fn` attribute
(the manager-registered entries follow that naming), subscripts /
attributes thereof, and names assigned from any of those.

Only HOT sites lacking a `# tpulint: sync-ok(<reason>)` pragma become
findings; the checked-in baseline absorbs the audited pre-existing
inventory. New hot syncs therefore fail CI until annotated or batched.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set

from .core import Finding, Package, Pragma, dotted

# qual suffixes of the per-iteration hot roots
_HOT_ROOT_SUFFIXES = (".train_one_iter", ".eval_at_iter")
_HOT_ROOT_FILES = ("lightgbm_tpu/boosting/", "lightgbm_tpu/engine.py")
_HOT_ROOT_EXACT = ("lightgbm_tpu/engine.py::_telemetry_end_iteration",)

# attribute-call names treated as producing device arrays
_DEVICE_FN_SUFFIXES = ("_jit", "_fn")


@dataclasses.dataclass
class SyncSite:
    rel: str
    line: int
    func: str          # enclosing function qual ("" at module level)
    code: str          # stable site descriptor ("device_get", ".item()", ...)
    hot: bool
    pragma: Optional[Pragma]

    @property
    def annotated(self) -> bool:
        return self.pragma is not None


def hot_roots(pkg: Package) -> List[str]:
    roots = [q for q in _HOT_ROOT_EXACT if q in pkg.functions]
    for q in pkg.functions:
        if q.startswith(_HOT_ROOT_FILES) and q.endswith(_HOT_ROOT_SUFFIXES):
            roots.append(q)
    return sorted(set(roots))


class _DeviceTaint(ast.NodeVisitor):
    """Names bound to likely-device values inside one function body."""

    def __init__(self, pkg: Package, rel: str) -> None:
        self.imps = pkg.imports[rel]
        self.devicey: Set[str] = set()

    def is_devicey(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.devicey
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self.is_devicey(node.value)
        if isinstance(node, ast.Attribute):
            if node.attr in ("shape", "ndim", "dtype", "size"):
                return False
            d = dotted(node)
            if d is not None and d in self.devicey:
                return True
            return self.is_devicey(node.value)
        if isinstance(node, ast.Call):
            fd = dotted(node.func)
            if fd is not None:
                root, leaf = fd.split(".")[0], fd.split(".")[-1]
                if leaf == "device_get":
                    return False    # the sync itself: result is host data
                if root in self.imps.numpy:
                    return False    # np.* results live on the host
                if root in (self.imps.jnp | self.imps.jax):
                    return True
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr.endswith(_DEVICE_FN_SUFFIXES):
                return True
            return any(self.is_devicey(a) for a in node.args)
        if isinstance(node, (ast.BinOp,)):
            return self.is_devicey(node.left) or self.is_devicey(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_devicey(node.operand)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_devicey(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.is_devicey(node.body) or self.is_devicey(node.orelse)
        return False

    def _bind(self, target: ast.AST, devicey: bool) -> None:
        # bind whole targets only ("x", "leaf.hist"), never the names
        # INSIDE a target — `self.a, b = dev, dev` must not taint `self`.
        # A host-valued rebind KILLS the taint: after
        # `x, y = jax.device_get((x, y))` the names hold host data.
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, devicey)
            return
        if isinstance(target, ast.Starred):
            self._bind(target.value, devicey)
            return
        if isinstance(target, ast.Subscript):
            if devicey:
                self._bind(target.value, devicey)
            return
        d = dotted(target)
        if d is not None:
            if devicey:
                self.devicey.add(d)
            else:
                self.devicey.discard(d)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        dev = self.is_devicey(node.value)
        for t in node.targets:
            self._bind(t, dev)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self.generic_visit(node)
        if node.value is not None:
            self._bind(node.target, self.is_devicey(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if self.is_devicey(node.value):
            self._bind(node.target, True)

    def visit_FunctionDef(self, node):  # nested: separate scope
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef


def _sites_in_function(pkg: Package, qual: str, hot: bool) -> List[SyncSite]:
    fi = pkg.functions[qual]
    sf = pkg.files[fi.rel]
    imps = pkg.imports[fi.rel]
    taint = _DeviceTaint(pkg, fi.rel)
    body = getattr(fi.node, "body", [])
    # two passes: bind device names first (source order suffices for the
    # package's straight-line hot loops), then collect sites
    for stmt in body:
        taint.visit(stmt)
    out: List[SyncSite] = []

    def add(node: ast.AST, code: str) -> None:
        out.append(SyncSite(fi.rel, node.lineno, qual, code, hot,
                            sf.pragma_at(node.lineno, "sync-ok")))

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            self.generic_visit(node)
            fd = dotted(node.func)
            if fd is not None:
                parts = fd.split(".")
                root, leaf = parts[0], parts[-1]
                if leaf == "device_get" and (root in imps.jax
                                             or len(parts) == 1):
                    add(node, "device_get")
                    return
                if root in imps.numpy and leaf in ("asarray", "array") \
                        and node.args and taint.is_devicey(node.args[0]):
                    add(node, f"np.{leaf}")
                    return
                if len(parts) == 1 and leaf in ("float", "int", "bool") \
                        and node.args and taint.is_devicey(node.args[0]):
                    add(node, f"{leaf}()")
                    return
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr == "block_until_ready":
                    add(node, ".block_until_ready()")
                elif attr in ("item", "tolist"):
                    add(node, f".{attr}()")

        def visit_FunctionDef(self, node):  # nested fns: own qual
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef

    v = V()
    for stmt in body:
        v.visit(stmt)
    return out


def inventory(pkg: Package) -> List[SyncSite]:
    """Every sync site in the package, classified hot vs. setup."""
    hot = pkg.reachable(hot_roots(pkg))
    sites: List[SyncSite] = []
    for qual in sorted(pkg.functions):
        sites.extend(_sites_in_function(pkg, qual, qual in hot))
    return sites


def hot_sites(pkg: Package) -> List[SyncSite]:
    return [s for s in inventory(pkg) if s.hot]


def is_trailing_fetch(site: SyncSite) -> bool:
    """A `# tpulint: sync-ok(trailing-fetch: ...)` site: the device_get
    resolves one pipeline step BEHIND its dispatch, so in steady state
    the value is already on the host and the call does not block. Such
    sites stay in the inventory (and in the runtime cross-check lines)
    but are excluded from the blocking-sync budget."""
    return site.pragma is not None and \
        site.pragma.reason.strip().startswith("trailing-fetch")


def hot_sync_count(pkg: Package) -> int:
    """Hot-loop sites that BLOCK the host — the number bench.py records
    as `hot_loop_syncs`. Trailing-fetch sites (see is_trailing_fetch)
    are excluded: their readback overlaps the next dispatch."""
    return len([s for s in hot_sites(pkg) if not is_trailing_fetch(s)])


def hot_site_lines(pkg: Package) -> Dict[str, Set[int]]:
    """rel -> line numbers of hot sync sites (for the transfer-guard
    runtime cross-check)."""
    out: Dict[str, Set[int]] = {}
    for s in hot_sites(pkg):
        out.setdefault(s.rel, set()).add(s.line)
    return out


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    for s in inventory(pkg):
        if s.hot and not s.annotated:
            findings.append(Finding(
                "sync-point", s.rel, s.line, s.func, s.code,
                f"{s.code} on the hot path (reachable from the training "
                "iteration loop); batch it or annotate "
                "`# tpulint: sync-ok(<reason>)`"))
    return findings
