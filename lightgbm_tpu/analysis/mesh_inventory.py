"""meshlint shared infrastructure: the mesh/axis inventory.

Answers two questions the device-side rule packs (collective-axis,
kernel-contract, dtype-flow) all need, from `ast` alone:

- which mesh axis names exist in this package (`axis_inventory`):
  string literals in `Mesh(devices, ("data",))` constructions /
  `axis_names=` kwargs, plus the axis literals named in
  `shard_map`/`pmap` partition specs. `dynamic` is set when a mesh is
  built with non-literal axis names (`f"axis{i}"` in
  `treelearner/parallel.py:build_mesh`) — those are accepted when they
  match the `axis<N>` pattern.
- which functions run *inside* a mapped region (`mapped_bodies`):
  every body handed to `shard_map` / `pmap`, in any of the repo's
  spellings — decorator, `functools.partial(shard_map, ...)` decorator,
  direct `shard_map(f, ...)` call, and the
  `functools.partial(shard_map, ...)(body)` call form. The
  `utils/compat.py` alias is recognized by leaf name, the same
  over-approximation trace_safety uses. Deliberately NOT recognized:
  `@lambda f: shard_map(f, ...)` decorators — an anonymous wrapper the
  call graph cannot see through; write the explicit call form instead.

Inside a mapped body every axis of the mesh is bound, so binding is
tracked per-package (the inventory), not per-site; reachability from
any mapped body is what the collective-axis pack checks.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import FunctionInfo, Package, dotted

_DYNAMIC_AXIS_RE = re.compile(r"axis\d+")

# kwargs of a shard_map/pmap site that carry axis-name literals
_SPEC_KWARGS = ("in_specs", "out_specs", "axis_name", "axis_names")


@dataclasses.dataclass
class AxisInventory:
    axes: Set[str]                       # literal axis names
    dynamic: bool                        # a Mesh uses computed axis names
    meshes: List[Tuple[str, int]]        # (rel, line) of Mesh constructions

    def permits(self, name: str) -> bool:
        """Is `name` a plausible axis of some mesh in this package?"""
        if name in self.axes:
            return True
        return self.dynamic and _DYNAMIC_AXIS_RE.fullmatch(name) is not None


def _axis_literals(node: ast.AST) -> Tuple[Set[str], bool]:
    """(string literals, saw-non-literal) anywhere under `node`."""
    names: Set[str] = set()
    non_literal = False
    for n in ast.walk(node):
        if isinstance(n, ast.Constant):
            if isinstance(n.value, str):
                names.add(n.value)
        elif isinstance(n, (ast.JoinedStr, ast.BinOp, ast.GeneratorExp,
                            ast.ListComp)):
            non_literal = True
    return names, non_literal


def axis_inventory(pkg: Package) -> AxisInventory:
    axes: Set[str] = set()
    dynamic = False
    meshes: List[Tuple[str, int]] = []
    for rel, sf in pkg.files.items():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            leaf = d.split(".")[-1] if d else None
            if leaf == "Mesh":
                meshes.append((rel, node.lineno))
                spec: Optional[ast.AST] = None
                if len(node.args) >= 2:
                    spec = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        spec = kw.value
                if spec is not None:
                    if isinstance(spec, (ast.Tuple, ast.List, ast.Constant)):
                        names, non_lit = _axis_literals(spec)
                        axes |= names
                        dynamic = dynamic or non_lit
                    else:
                        # axis names computed elsewhere (build_mesh's
                        # `axes = tuple(f"axis{i}" ...) + ("data",)`
                        # variable): treat as dynamic, and pick up any
                        # literals for the expression forms
                        names, _ = _axis_literals(spec)
                        axes |= names
                        dynamic = True
            elif leaf in ("shard_map", "pmap"):
                for kw in node.keywords:
                    if kw.arg in _SPEC_KWARGS:
                        names, _ = _axis_literals(kw.value)
                        axes |= names
    return AxisInventory(axes, dynamic, meshes)


def _is_mapping_name(node: ast.AST) -> Optional[str]:
    """'shard_map' | 'pmap' when `node` names that transform (any
    alias/attribute spelling, including the utils/compat shim)."""
    d = dotted(node)
    if d is None:
        return None
    leaf = d.split(".")[-1]
    return leaf if leaf in ("shard_map", "pmap") else None


def mapped_bodies(pkg: Package) -> Dict[str, int]:
    """qual -> definition line, for every function that is the body of a
    `shard_map`/`pmap` site. These are the roots from which collectives
    are legitimately reachable."""
    out: Dict[str, int] = {}

    def add(rel: str, caller: Optional[FunctionInfo],
            target: ast.AST) -> None:
        if isinstance(target, ast.Lambda):
            return  # collectives in lambda bodies get no qualname anyway
        for q in pkg.resolve_call(rel, caller, target, fallback=False):
            fi = pkg.functions.get(q)
            if fi is not None:
                out[q] = fi.lineno

    for rel, sf in pkg.files.items():
        # decorator forms: @shard_map-ish / @functools.partial(shard_map,..)
        for qual, fi in pkg.functions.items():
            if fi.rel != rel:
                continue
            for dec in getattr(fi.node, "decorator_list", []):
                if _is_mapping_name(dec) is not None:
                    out[qual] = fi.lineno
                    continue
                if isinstance(dec, ast.Call):
                    if _is_mapping_name(dec.func) is not None:
                        out[qual] = fi.lineno
                        continue
                    fd = dotted(dec.func)
                    if fd is not None and fd.split(".")[-1] == "partial" \
                            and dec.args \
                            and _is_mapping_name(dec.args[0]) is not None:
                        out[qual] = fi.lineno
        # call forms: shard_map(f, ...) / partial(shard_map, ...)(body)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            caller = pkg.enclosing_function(rel, node)
            if _is_mapping_name(node.func) is not None and node.args:
                add(rel, caller, node.args[0])
            elif isinstance(node.func, ast.Call):
                fd = dotted(node.func.func)
                if fd is not None and fd.split(".")[-1] == "partial" \
                        and node.func.args \
                        and _is_mapping_name(node.func.args[0]) is not None \
                        and node.args:
                    add(rel, caller, node.args[0])
    return out


def self_attr_constants(pkg: Package) -> Dict[str, Set[object]]:
    """attr name -> set of constant values ever assigned package-wide as
    `self.<attr> = <constant>`. Used to resolve attribute axis
    arguments (`self.psum_axis`) at collective sites; a non-constant
    assignment poisons the attr (maps to {Ellipsis} marker)."""
    out: Dict[str, Set[object]] = {}
    for sf in pkg.files.values():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    if isinstance(node.value, ast.Constant):
                        out.setdefault(tgt.attr, set()).add(node.value.value)
                    else:
                        out.setdefault(tgt.attr, set()).add(Ellipsis)
    return out
