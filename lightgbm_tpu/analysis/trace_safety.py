"""Rule pack: trace-safety.

Flags implicit tracer concretization inside functions reachable from
`jax.jit` / `lax.scan` / `shard_map` bodies:

- `np.asarray` / `np.array` / `jax.device_get` / `.item()` / `.tolist()`
  applied to an expression containing a *traced* value,
- `float()` / `int()` / `bool()` on a traced expression,
- Python `if` / `while` whose test reads a traced value directly
  (a trace-time `TracerBoolConversionError` in waiting),
- Python `for` iterating a traced array.

"Traced" is a syntactic taint: the non-static parameters of a jit root,
propagated through name assignments inside the function and through
name-resolved calls into callees (positional + keyword mapping, run to
a fixpoint). Shape/metadata reads (`x.shape`, `x.ndim`, `x.dtype`,
`x.size`, `len(x)`, `x is None`) are exempt — they are static under
tracing.

Suppress a deliberate site with `# tpulint: trace-ok(<reason>)`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FunctionInfo, Package, dotted

_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "weak_type", "nbytes"}
_CONCRETIZING_METHODS = {"item", "tolist", "block_until_ready"}
_NP_CONCRETIZING = {"asarray", "array", "copy", "save", "savez"}
_BUILTIN_CONCRETIZING = {"float", "int", "bool", "complex"}


def _static_names_from_jit(call: ast.Call, params: List[str]) -> Set[str]:
    """Parameter names made static by static_argnums/static_argnames."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(node.value,
                                                                 str):
                    out.add(node.value)
        elif kw.arg == "static_argnums":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(node.value,
                                                                 int):
                    if 0 <= node.value < len(params):
                        out.add(params[node.value])
    return out


def _is_jit_name(pkg: Package, rel: str, node: ast.AST) -> Optional[str]:
    """'jit' | 'scan' | 'shard_map' when `node` names that transform."""
    d = dotted(node)
    if d is None:
        return None
    imps = pkg.imports[rel]
    parts = d.split(".")
    root = parts[0]
    if parts[-1] == "jit" and (root in imps.jax or root == "jax"
                               or len(parts) == 1):
        # jax.jit / <alias>.jit; bare "jit" only if imported from jax
        if len(parts) == 1 and root != "jit":
            return None
        if len(parts) == 1:
            sym = imps.symbols.get("jit")
            return None if sym is not None else "jit"
        return "jit"
    if parts[-1] == "scan" and (root in imps.jax or "lax" in parts
                                or root == "lax"):
        return "scan"
    if parts[-1] == "shard_map":
        return "shard_map"
    return None


class _JitRoots:
    """Jit/scan/shard_map entry functions + their static params."""

    def __init__(self, pkg: Package) -> None:
        self.pkg = pkg
        # qual -> set of static param names
        self.roots: Dict[str, Set[str]] = {}
        # Lambda nodes used as jit/scan bodies: (rel, lambda node, statics)
        self.lambdas: List[Tuple[str, ast.Lambda, Set[str]]] = []
        for rel, sf in pkg.files.items():
            self._scan_module(rel, sf.tree)

    def _add_target(self, rel: str, caller: Optional[FunctionInfo],
                    target: ast.AST, statics_call: Optional[ast.Call]
                    ) -> None:
        if isinstance(target, ast.Lambda):
            statics = set()
            if statics_call is not None:
                statics = _static_names_from_jit(statics_call,
                                                 _lambda_params(target))
            self.lambdas.append((rel, target, statics))
            return
        for q in self.pkg.resolve_call(rel, caller, target, fallback=False):
            fi = self.pkg.functions.get(q)
            if fi is None:
                continue
            statics: Set[str] = set()
            if statics_call is not None:
                params = fi.params
                if fi.cls and params and params[0] == "self":
                    pass  # static_argnums count from the bound signature
                statics = _static_names_from_jit(statics_call, params)
            self.roots.setdefault(q, set()).update(statics)

    def _scan_module(self, rel: str, tree: ast.Module) -> None:
        pkg = self.pkg
        # decorators: @jax.jit / @functools.partial(jax.jit, ...) /
        # @functools.partial(shard_map, ...)
        for qual, fi in pkg.functions.items():
            if fi.rel != rel:
                continue
            for dec in getattr(fi.node, "decorator_list", []):
                kind = _is_jit_name(pkg, rel, dec)
                if kind is not None:
                    self.roots.setdefault(qual, set())
                    continue
                if isinstance(dec, ast.Call):
                    kind = _is_jit_name(pkg, rel, dec.func)
                    if kind is not None:
                        statics = _static_names_from_jit(dec, fi.params)
                        self.roots.setdefault(qual, set()).update(statics)
                        continue
                    # functools.partial(jax.jit, ...) or partial(shard_map,..)
                    fd = dotted(dec.func)
                    if fd is not None and fd.split(".")[-1] == "partial" \
                            and dec.args:
                        inner = _is_jit_name(pkg, rel, dec.args[0])
                        if inner is not None:
                            statics = _static_names_from_jit(dec, fi.params)
                            self.roots.setdefault(qual, set()).update(statics)
        # call sites: jax.jit(f, ...), lax.scan(f, ...), shard_map(f, ...)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _is_jit_name(pkg, rel, node.func)
            caller = pkg.enclosing_function(rel, node)
            if kind in ("jit", "shard_map") and node.args:
                self._add_target(rel, caller, node.args[0],
                                 node if kind == "jit" else None)
            elif kind == "scan" and node.args:
                self._add_target(rel, caller, node.args[0], None)
            else:
                # partial(shard_map, mesh=...)(body) / partial(jax.jit,..)(f)
                if isinstance(node.func, ast.Call):
                    fd = dotted(node.func.func)
                    if fd is not None and fd.split(".")[-1] == "partial" \
                            and node.func.args:
                        inner = _is_jit_name(pkg, rel, node.func.args[0])
                        if inner is not None and node.args:
                            self._add_target(rel, caller, node.args[0],
                                             node.func if inner == "jit"
                                             else None)


def _lambda_params(lam: ast.Lambda) -> List[str]:
    a = lam.args
    out = [p.arg for p in getattr(a, "posonlyargs", [])] + \
        [p.arg for p in a.args]
    if a.vararg:
        out.append(a.vararg.arg)
    out += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        out.append(a.kwarg.arg)
    return out


def _expr_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    """True when `node` reads a tainted name OUTSIDE the shape/metadata
    exemptions."""
    if isinstance(node, ast.Attribute):
        if node.attr in _SHAPE_ATTRS:
            return False
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Call):
        fd = dotted(node.func)
        if fd is not None and fd.split(".")[0] == "len":
            return False          # len(traced) is static rank info
        parts = [node.func] if not isinstance(node.func, ast.Name) else []
        sub = parts + list(node.args) + [kw.value for kw in node.keywords]
        return any(_expr_tainted(c, tainted) for c in sub)
    if isinstance(node, ast.Compare):
        ops_ok = all(isinstance(op, (ast.Is, ast.IsNot))
                     for op in node.ops)
        if ops_ok:
            return False          # `x is None` style checks are static
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.Load, ast.Store, ast.Del, ast.operator,
                              ast.cmpop, ast.boolop, ast.unaryop)):
            continue
        if _expr_tainted(child, tainted):
            return True
    return False


class _BodyChecker(ast.NodeVisitor):
    """Scan one traced function body with a known tainted-name set,
    updating taint through assignments in source order."""

    def __init__(self, pkg: Package, rel: str, fi_qual: str,
                 tainted: Set[str], findings: List[Finding],
                 call_taints: Dict[str, Set[str]],
                 caller: Optional[FunctionInfo]) -> None:
        self.pkg = pkg
        self.rel = rel
        self.sf = pkg.files[rel]
        self.qual = fi_qual
        self.tainted = set(tainted)
        self.findings = findings
        self.call_taints = call_taints      # callee qual -> tainted params
        self.caller = caller
        self.imps = pkg.imports[rel]

    # -- taint bookkeeping ---------------------------------------------
    def _taint_targets(self, target: ast.AST) -> None:
        # `self.x = tainted` must not taint `self` wholesale
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._taint_targets(e)
        elif isinstance(target, (ast.Starred, ast.Subscript)):
            self._taint_targets(target.value)
        elif isinstance(target, ast.Name):
            self.tainted.add(target.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if _expr_tainted(node.value, self.tainted):
            for t in node.targets:
                self._taint_targets(t)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if _expr_tainted(node.value, self.tainted):
            self._taint_targets(node.target)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self.generic_visit(node)
        if node.value is not None and _expr_tainted(node.value, self.tainted):
            self._taint_targets(node.target)

    # nested defs are separate functions; don't descend
    def visit_FunctionDef(self, node):  # noqa: D102
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):  # noqa: D102
        pass

    # -- checks ---------------------------------------------------------
    def _emit(self, node: ast.AST, code: str, message: str) -> None:
        if self.sf.pragma_at(node.lineno, "trace-ok"):
            return
        self.findings.append(Finding("trace-safety", self.rel, node.lineno,
                                     self.qual, code, message))

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        fd = dotted(node.func)
        args_tainted = any(_expr_tainted(a, self.tainted) for a in node.args)
        if fd is not None:
            parts = fd.split(".")
            root, leaf = parts[0], parts[-1]
            if root in self.imps.numpy and leaf in _NP_CONCRETIZING \
                    and args_tainted:
                self._emit(node, f"np.{leaf}",
                           f"np.{leaf}() concretizes a traced value inside "
                           "jitted code")
                return
            if leaf == "device_get" and args_tainted:
                self._emit(node, "device_get",
                           "jax.device_get() inside traced code forces a "
                           "sync + concretization")
                return
            if len(parts) == 1 and leaf in _BUILTIN_CONCRETIZING \
                    and args_tainted:
                self._emit(node, f"{leaf}()",
                           f"{leaf}() on a traced value raises/concretizes "
                           "at trace time")
                return
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _CONCRETIZING_METHODS \
                and _expr_tainted(node.func.value, self.tainted):
            self._emit(node, f".{node.func.attr}()",
                       f".{node.func.attr}() concretizes a traced value "
                       "inside jitted code")
            return
        # propagate taint into CONFIDENTLY resolved package callees:
        # the simple-name fallback would taint every `add`/`update` in
        # the package off dict/set method calls
        for q in self.pkg.resolve_call(self.rel, self.caller, node.func,
                                       fallback=False):
            fi = self.pkg.functions.get(q)
            if fi is None:
                continue
            params = fi.params
            off = 1 if (fi.cls and params and params[0] in ("self", "cls")
                        and isinstance(node.func, ast.Attribute)) else 0
            newly: Set[str] = set()
            for i, a in enumerate(node.args):
                if i + off < len(params) and _expr_tainted(a, self.tainted):
                    newly.add(params[i + off])
            for kw in node.keywords:
                if kw.arg in params and _expr_tainted(kw.value, self.tainted):
                    newly.add(kw.arg)
            if newly - self.call_taints.get(q, set()):
                self.call_taints.setdefault(q, set()).update(newly)

    def visit_If(self, node: ast.If) -> None:
        if _expr_tainted(node.test, self.tainted):
            self._emit(node, "if-traced",
                       "Python `if` on a traced value (trace-time bool "
                       "conversion)")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if _expr_tainted(node.test, self.tainted):
            self._emit(node, "while-traced",
                       "Python `while` on a traced value")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        # `for v in traced:` — iterating a tracer; range(x.shape[0]) is
        # exempt via the shape-attr exemption inside _expr_tainted
        if _expr_tainted(node.iter, self.tainted):
            self._emit(node, "for-traced",
                       "Python `for` over a traced array (unrolls / "
                       "concretizes)")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if _expr_tainted(node.test, self.tainted):
            self._emit(node, "assert-traced",
                       "assert on a traced value")
        self.generic_visit(node)


def traced_functions(pkg: Package) -> Dict[str, Set[str]]:
    """qual -> tainted params, for every function reachable from a jit/
    scan/shard_map root (fixpoint over the call graph)."""
    roots = _JitRoots(pkg)
    taints: Dict[str, Set[str]] = {}
    for q, statics in roots.roots.items():
        fi = pkg.functions[q]
        params = [p for p in fi.params if p not in ("self", "cls")]
        taints[q] = {p for p in params if p not in statics}
    # fixpoint: run body checks only for taint PROPAGATION (findings
    # discarded), until the callee taint map stops growing
    for _ in range(6):
        before = {q: set(s) for q, s in taints.items()}
        sink: List[Finding] = []
        for q in list(taints):
            fi = pkg.functions.get(q)
            if fi is None:
                continue
            chk = _BodyChecker(pkg, fi.rel, q, taints[q], sink, taints, fi)
            for stmt in fi.node.body if hasattr(fi.node, "body") else []:
                chk.visit(stmt)
        if {q: s for q, s in taints.items()} == before:
            break
    return taints


def check(pkg: Package) -> List[Finding]:
    taints = traced_functions(pkg)
    findings: List[Finding] = []
    for q, tainted in sorted(taints.items()):
        fi = pkg.functions.get(q)
        if fi is None or not tainted:
            continue
        chk = _BodyChecker(pkg, fi.rel, q, tainted, findings, taints, fi)
        for stmt in fi.node.body if hasattr(fi.node, "body") else []:
            chk.visit(stmt)
    return findings
