"""Rule pack: dtype-flow.

Taint-tracks the f32 -> int16 -> packed-int32 -> f32 conversions of the
quantized histogram pipeline (`ops/quantize.py`) through each function
body and flags the orderings that silently lose precision:

- **narrow-sum** — `jnp.sum(x)` / `x.sum()` on a value known to be
  int16/int8/uint16/uint8/bfloat16 without a `dtype=` widening kwarg:
  jnp reductions accumulate in the *input* dtype, so a histogram of
  int16 gradients overflows at 2^15.
- **packed-as-float** — `.astype(float32)` on a packed gh word
  (`pack_gh` / `pairs_to_packed_hist` result): a *value* cast of bit-
  packed fields is meaningless; unpack first (`unpack_gh` /
  `packed_hist_to_pairs`), or bitcast if the raw bits are wanted.
- **dequant-before-subtract** — both operands of a subtraction were
  separately converted int -> float before the subtract: the sibling-
  histogram trick is exact only in int32
  (`parent - sibling` THEN dequantize); in f32 the rounding of two
  large nearly-equal sums cancels catastrophically.
- **accum-downcast** — `acc.at[i].add(v)` where `acc` is known narrow
  (int16/int8) and `v` known wider (int32/f32): every add round-trips
  through the narrow dtype regardless of v's precision.

Tracking is per-function and syntactic: dtypes come from `.astype(T)`,
`jnp.zeros/ones/full/empty(..., dtype=T)`, and the quantize-pipeline
producers (`pack_gh`/`pairs_to_packed_hist` -> packed,
`unpack_gh` -> int16 pair, `packed_hist_to_pairs` -> int32,
`quantize_gradients` -> int16s). No interprocedural guessing — a dtype
the pack can't prove is not flagged.

Suppress a deliberate site with `# tpulint: dtype-ok(<reason>)`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .core import Finding, Package, dotted

_NARROW = {"int16", "uint16", "int8", "uint8", "bfloat16", "float16"}
_WIDE = {"int32", "uint32", "int64", "float32", "float64"}
_FLOAT = {"float32", "float64", "bfloat16", "float16"}
_INT = {"int8", "uint8", "int16", "uint16", "int32", "uint32", "int64"}

# quantize-pipeline producers -> dtype marker of their result
_PRODUCERS = {
    "pack_gh": "packed",
    "pairs_to_packed_hist": "packed",
    "packed_hist_to_pairs": "int32",
    "unpack_gh": "int16",            # (qg, qh) int16 pair
    "quantize_gradients": "int16",
}

_ZERO_MAKERS = {"zeros", "ones", "full", "empty", "zeros_like", "ones_like",
                "full_like", "empty_like"}


def _walk_local(fn_node: ast.AST):
    """ast.walk (breadth-first, so same-level statements keep source
    order — assignment recording depends on it) without descending
    into nested function/class defs: those are separate FunctionInfos
    and get their own checker."""
    from collections import deque
    queue = deque(ast.iter_child_nodes(fn_node))
    while queue:
        node = queue.popleft()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        queue.extend(ast.iter_child_nodes(node))


def _dtype_leaf(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    d = dotted(node)
    if d is not None:
        leaf = d.split(".")[-1]
        if leaf in _NARROW | _WIDE or leaf in ("float32", "int32"):
            return leaf
    return None


class _FnChecker:
    """One function body: assignment-ordered dtype map + checks."""

    def __init__(self, pkg: Package, rel: str, qual: str,
                 fn_node: ast.AST, findings: List[Finding]) -> None:
        self.pkg = pkg
        self.rel = rel
        self.sf = pkg.files[rel]
        self.qual = qual
        self.fn = fn_node
        self.findings = findings
        self.dtype: Dict[str, str] = {}

    def _emit(self, node: ast.AST, code: str, message: str) -> None:
        if self.sf.pragma_at(node.lineno, "dtype-ok"):
            return
        self.findings.append(Finding("dtype-flow", self.rel, node.lineno,
                                     self.qual, code, message))

    # -- dtype of an expression, from the map + producing calls ---------
    def _dtype_of(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.dtype.get(expr.id)
        if isinstance(expr, ast.Call):
            # x.astype(T)
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr == "astype" and expr.args:
                return _dtype_leaf(expr.args[0])
            d = dotted(expr.func)
            leaf = d.split(".")[-1] if d else None
            if leaf in _PRODUCERS:
                return _PRODUCERS[leaf]
            if leaf in _ZERO_MAKERS:
                for kw in expr.keywords:
                    if kw.arg == "dtype":
                        return _dtype_leaf(kw.value)
                if len(expr.args) > 1:
                    return _dtype_leaf(expr.args[1])
        if isinstance(expr, ast.Subscript):
            return self._dtype_of(expr.value)
        return None

    def _was_int(self, expr: ast.AST) -> bool:
        """Did `expr` convert an int value to float right here
        (`<int>.astype(float)`), or is it a name assigned that way?"""
        if isinstance(expr, ast.Name):
            return self.dtype.get(expr.id) == "float-from-int"
        if isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr == "astype" and expr.args:
            dst = _dtype_leaf(expr.args[0])
            src = self._dtype_of(expr.func.value)
            return dst in _FLOAT and (src in _INT or src == "packed")
        return False

    # -- per-statement walk ---------------------------------------------
    def _record_assign(self, node: ast.Assign) -> None:
        if len(node.targets) != 1:
            return
        tgt = node.targets[0]
        dt = self._dtype_of(node.value)
        if self._was_int(node.value):
            dt = "float-from-int"
        if dt is None:
            return
        if isinstance(tgt, ast.Name):
            self.dtype[tgt.id] = dt
        elif isinstance(tgt, ast.Tuple) and dt in ("int16",):
            # qg, qh = unpack_gh(w) / quantize_gradients(...)
            for e in tgt.elts:
                if isinstance(e, ast.Name):
                    self.dtype[e.id] = dt

    def _check_call(self, node: ast.Call) -> None:
        d = dotted(node.func)
        leaf = d.split(".")[-1] if d else None
        # narrow-sum: jnp.sum(x) / x.sum() without dtype=
        if leaf in ("sum", "cumsum", "prod"):
            has_dtype = any(kw.arg == "dtype" for kw in node.keywords)
            operand: Optional[ast.AST] = None
            if isinstance(node.func, ast.Attribute):
                root = d.split(".")[0] if d else None
                imps = self.pkg.imports[self.rel]
                if root in (imps.jnp | imps.numpy | imps.jax):
                    operand = node.args[0] if node.args else None
                else:
                    operand = node.func.value      # x.sum()
            if operand is not None and not has_dtype:
                dt = self._dtype_of(operand)
                if dt in _NARROW:
                    self._emit(node, f"narrow-sum:{dt}",
                               f"{leaf}() over a {dt} value accumulates "
                               f"in {dt} (jnp reductions keep the input "
                               "dtype) — pass dtype=jnp.int32/float32")
        # packed-as-float: <packed>.astype(float)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype" and node.args:
            dst = _dtype_leaf(node.args[0])
            src = self._dtype_of(node.func.value)
            if src == "packed" and dst in _FLOAT:
                self._emit(node, "packed-as-float",
                           "value-cast of a packed gh word to float — "
                           "unpack first (packed_hist_to_pairs/unpack_gh) "
                           "or bitcast for raw bits")
        # accum-downcast: acc.at[i].add(v)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("add", "set") and node.args:
            base = node.func.value
            if isinstance(base, ast.Subscript) \
                    and isinstance(base.value, ast.Attribute) \
                    and base.value.attr == "at":
                acc_dt = self._dtype_of(base.value.value)
                val_dt = self._dtype_of(node.args[0])
                if acc_dt in _NARROW and val_dt in _WIDE:
                    self._emit(node, f"accum-downcast:{acc_dt}<-{val_dt}",
                               f".at[].{node.func.attr}() of a {val_dt} "
                               f"value into a {acc_dt} accumulator rounds "
                               "through the narrow dtype on every update")

    def _check_binop(self, node: ast.BinOp) -> None:
        if isinstance(node.op, ast.Sub) \
                and self._was_int(node.left) and self._was_int(node.right):
            self._emit(node, "dequant-before-subtract",
                       "both operands were dequantized to float before "
                       "the subtract — histogram subtraction is exact "
                       "only in int32: subtract first, then convert")

    def run(self) -> None:
        for node in _walk_local(self.fn):
            if isinstance(node, ast.Assign):
                self._record_assign(node)
        # second pass with the full map (walk order is not source order;
        # per-function maps are tiny, so two passes beat bookkeeping)
        for node in _walk_local(self.fn):
            if isinstance(node, ast.Call):
                self._check_call(node)
            elif isinstance(node, ast.BinOp):
                self._check_binop(node)


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    for qual in sorted(pkg.functions):
        fi = pkg.functions[qual]
        _FnChecker(pkg, fi.rel, qual, fi.node, findings).run()
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings
