"""Rule pack: buffer-lifetime ("lifelint", donation half).

The pipelined loop donates its double-buffered planar state into every
iteration dispatch (`donate_argnums` on the compile-manager entries)
and lets readbacks trail their dispatch by a whole pipeline step
(`copy_to_host_async` handles resolved one period later). Both are
invisible on the CPU tier-1 suite — donation is a no-op there and an
undrained handle just resolves late — and both corrupt silently on
real TPU HBM: a read of a donated buffer observes whatever the aliased
output wrote, and a handle outliving its source fetches freed memory.

What is checked
---------------
1. **use-after-donate** — a binding passed in a donated position of a
   donating callable is DEAD after the call statement; any later read
   of it in the same function without an intervening rebind is a
   finding. The canonical safe shape rebinds in the same statement:
   `state = entry(state, ...)`.
2. **donate-escape-closure** — a binding that is donated anywhere in a
   function must not be captured by a nested function/lambda defined
   in that function: the closure typically runs later (warmup thread,
   callback) against a buffer that no longer exists.
3. **escape-checkpoint / escape-flight / escape-telemetry** — device
   values (per the sync_points device-taint heuristic) must not be
   stored into checkpoint state (`checkpoint_state` methods — the PR 8
   `_drain_stop_check` discipline, generalized: robust/checkpoint.py
   payloads must be device-ref-free), flight-recorder dump payloads,
   or telemetry gauges/counters. Launder through `np.asarray`, `jax.
   device_get`, `int`/`float`/`bool` first.
4. **fetch-no-drain / fetch-ckpt-live** — a class that parks
   `copy_to_host_async` handles on an instance attribute must own a
   drain (some method resets the attribute), and its
   `checkpoint_state` must reach that drain: a checkpoint must never
   carry live device refs.

Donating callables are discovered statically: attributes/locals bound
from `jax.jit(..., donate_argnums=...)`, `*.shared_entry(...,
donate_argnums=...)` or `*.jit_entry(..., donate_argnums=...)`
(compile/manager.py), looked through `instrument_kernel(...)` wrappers
and through methods that merely forward a parameter into a donated
position (`train_iter_persistent` donates its `data` argument).

Suppress with `# tpulint: donate-ok(<reason>)` on the offending line
or the line above. Analysis is function-local and source-order (no
back-edge tracking through loops): over-approximation is a pragma
away from quiet, an unflagged use-after-donate is silent corruption.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FunctionInfo, Package, dotted
from .sync_points import _DeviceTaint

RULE = "buffer-lifetime"

# factory callables whose result donates (positions from the literal
# donate_argnums keyword)
_ENTRY_FACTORIES = ("shared_entry", "jit_entry")
# wrappers that preserve donation semantics of their first argument
_TRANSPARENT_WRAPPERS = ("instrument_kernel",)

# conversions that launder a device value into host data
_LAUNDER_CALLS = {"asarray", "array", "device_get", "int", "float",
                  "bool", "str", "len", "list", "tuple", "dict"}

# methods whose return payload must stay device-ref-free
_CKPT_METHOD_NAMES = ("checkpoint_state",)

# attribute-call receivers treated as a flight-recorder dump
_FLIGHT_DUMP_ATTR = "dump"
# telemetry publication calls (second positional arg is the payload)
_TELEMETRY_CALLS = ("set_gauge", "inc", "observe", "add_time",
                    "observe_latency")


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums of a Call, or None when absent/dynamic."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
                else:
                    return None
            return tuple(out)
        return None
    return None


@dataclasses.dataclass
class DonationSite:
    """One statically-discovered donating registration."""
    rel: str
    line: int
    func: str                 # enclosing function qual
    entry_name: str           # literal entry name ("" for bare jax.jit)
    positions: Tuple[int, ...]


class _ModuleDonations:
    """Donating bindings of one module: class attrs, locals, and
    wrapper functions, each mapped to donated positional indices."""

    def __init__(self, pkg: Package, rel: str) -> None:
        self.pkg = pkg
        self.rel = rel
        # (cls or "", attr/local name) -> donated positions
        self.attrs: Dict[Tuple[str, str], Tuple[int, ...]] = {}
        # qual -> positions, for functions RETURNING a donating callable
        self.wrappers_returning: Dict[str, Tuple[int, ...]] = {}
        self.sites: List[DonationSite] = []

    # -- classification of value expressions ----------------------------
    def _expr_positions(self, node: ast.AST, fi: FunctionInfo,
                        local: Dict[str, Tuple[int, ...]],
                        record_site: bool = False
                        ) -> Optional[Tuple[int, ...]]:
        """Donated positions of the callable this expression evaluates
        to, or None when it is not a donating callable."""
        if isinstance(node, ast.Name):
            return local.get(node.id)
        a = _self_attr(node)
        if a is not None:
            return self.attrs.get((fi.cls or "", a))
        if not isinstance(node, ast.Call):
            return None
        fd = dotted(node.func)
        leaf = fd.split(".")[-1] if fd else ""
        if not leaf and isinstance(node.func, ast.Attribute):
            # non-Name receiver chain: `get_manager().shared_entry(...)`
            leaf = node.func.attr
        if leaf == "jit":
            pos = _donate_positions(node)
            if pos:
                if record_site:
                    self.sites.append(DonationSite(
                        self.rel, node.lineno, fi.qual, "", pos))
                return pos
            return None
        if leaf in _ENTRY_FACTORIES:
            pos = _donate_positions(node)
            if pos:
                name = ""
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    name = node.args[0].value
                if record_site:
                    self.sites.append(DonationSite(
                        self.rel, node.lineno, fi.qual, name, pos))
                return pos
            return None
        if leaf in _TRANSPARENT_WRAPPERS and node.args:
            return self._expr_positions(node.args[0], fi, local,
                                        record_site)
        # self-method call returning a donating callable
        # (`self._iters_scan_jit_build(k)`)
        callees = self.pkg.resolve_call(self.rel, fi, node.func,
                                        fallback=False)
        for q in callees:
            if q in self.wrappers_returning:
                return self.wrappers_returning[q]
        return None

    def collect(self) -> None:
        # two passes: pass 1 binds direct registrations, pass 2 looks
        # through instrument_kernel / returning-method indirection
        for _ in range(2):
            for qual, fi in self.pkg.functions.items():
                if fi.rel != self.rel:
                    continue
                assigns, returns, _ = _fn_index(fi)
                local: Dict[str, Tuple[int, ...]] = {}
                for stmt in assigns:
                    pos = self._expr_positions(stmt.value, fi, local,
                                               record_site=False)
                    if pos is None:
                        continue
                    for t in stmt.targets:
                        tgt = t.value if isinstance(t, ast.Subscript) \
                            else t
                        a = _self_attr(tgt)
                        if a is not None:
                            self.attrs[(fi.cls or "", a)] = pos
                        elif isinstance(tgt, ast.Name):
                            local[tgt.id] = pos
                for stmt in returns:
                    if stmt.value is None:
                        continue
                    pos = self._expr_positions(stmt.value, fi, local)
                    if pos is not None:
                        self.wrappers_returning[qual] = pos
        # record inventory sites once (third pass, sites deduped by line)
        for qual, fi in self.pkg.functions.items():
            if fi.rel != self.rel:
                continue
            assigns, returns, _ = _fn_index(fi)
            local2: Dict[str, Tuple[int, ...]] = {}
            for stmt in assigns:
                self._expr_positions(stmt.value, fi, local2,
                                     record_site=True)
            for stmt in returns:
                if stmt.value is not None:
                    self._expr_positions(stmt.value, fi, local2,
                                         record_site=True)


def _fn_index(fi: FunctionInfo
              ) -> Tuple[List[ast.Assign], List[ast.Return],
                         List[ast.Call]]:
    """Assign/Return/Call nodes of one function, walked once and
    memoized on the FunctionInfo — the donation model visits every
    function ~6 times (collect passes, wrapper fixpoint, rule scans)
    and re-walking dominates the pack's runtime."""
    idx = getattr(fi, "_life_index", None)
    if idx is None:
        assigns: List[ast.Assign] = []
        returns: List[ast.Return] = []
        calls: List[ast.Call] = []
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign):
                assigns.append(n)
            elif isinstance(n, ast.Return):
                returns.append(n)
            elif isinstance(n, ast.Call):
                calls.append(n)
        idx = (assigns, returns, calls)
        fi._life_index = idx
    return idx


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _binding(node: ast.AST) -> Optional[Tuple[str, str]]:
    """Trackable donated binding: ("name", x) or ("attr", x)."""
    if isinstance(node, ast.Name):
        return ("name", node.id)
    a = _self_attr(node)
    if a is not None:
        return ("attr", a)
    return None


class _Donations:
    """Package-wide donation model."""

    def __init__(self, pkg: Package) -> None:
        self.pkg = pkg
        self.modules: Dict[str, _ModuleDonations] = {}
        # per-function memos: the donating-locals map and the literal
        # tuple map depend only on module-level donation state, which
        # is fixed after collect() — recomputing them per call site
        # turns the pack quadratic on large modules
        self._locals_cache: Dict[str, Dict[str, Tuple[int, ...]]] = {}
        self._tuples_cache: Dict[str, Dict[str, List[ast.AST]]] = {}
        for rel in pkg.files:
            md = _ModuleDonations(pkg, rel)
            md.collect()
            self.modules[rel] = md
        # wrapper methods that forward a param into a donated position:
        # qual -> donated call positions (bound-method view, self
        # stripped). Iterate to a small fixpoint so wrappers of
        # wrappers resolve (depth 2 covers the package).
        self.method_wrappers: Dict[str, Tuple[int, ...]] = {}
        for _ in range(2):
            for qual, fi in pkg.functions.items():
                pos = self._wrapper_positions(fi)
                if pos:
                    self.method_wrappers[qual] = pos

    # -- donating call detection ----------------------------------------
    def call_positions(self, fi: FunctionInfo, call: ast.Call,
                       local_tuples: Dict[str, List[ast.AST]]
                       ) -> Optional[Tuple[int, ...]]:
        """Donated positions of one call expression, or None."""
        md = self.modules[fi.rel]
        f = call.func
        if isinstance(f, ast.Subscript):
            f = f.value
        if isinstance(f, ast.Name):
            # locals are per-collect-pass; re-derive cheaply
            pos = self._local_positions(fi, f.id)
            if pos is not None:
                return pos
        a = _self_attr(f)
        if a is not None:
            pos = md.attrs.get((fi.cls or "", a))
            if pos is not None:
                return pos
        # method call on another object: confident resolution first,
        # unique simple-name fallback second (a taint analysis must not
        # let `x.update(...)` hit every `update` in the package)
        callees = self.pkg.resolve_call(fi.rel, fi, call.func,
                                        fallback=False)
        if not callees and isinstance(call.func, ast.Attribute):
            cands = self.pkg.by_name.get(call.func.attr, [])
            if len(cands) == 1:
                callees = set(cands)
        for q in callees:
            if q in self.method_wrappers:
                return self.method_wrappers[q]
        return None

    def _local_positions(self, fi: FunctionInfo, name: str
                         ) -> Optional[Tuple[int, ...]]:
        local = self._locals_cache.get(fi.qual)
        if local is None:
            md = self.modules[fi.rel]
            local = {}
            for stmt in _fn_index(fi)[0]:
                pos = md._expr_positions(stmt.value, fi, local)
                if pos is None:
                    continue
                for t in stmt.targets:
                    tgt = t.value if isinstance(t, ast.Subscript) \
                        else t
                    if isinstance(tgt, ast.Name):
                        local[tgt.id] = pos
            self._locals_cache[fi.qual] = local
        return local.get(name)

    def local_tuples(self, fi: FunctionInfo) -> Dict[str, List[ast.AST]]:
        tuples = self._tuples_cache.get(fi.qual)
        if tuples is None:
            tuples = _local_tuples(fi.node)
            self._tuples_cache[fi.qual] = tuples
        return tuples

    def donated_args(self, fi: FunctionInfo, call: ast.Call,
                     positions: Tuple[int, ...],
                     local_tuples: Dict[str, List[ast.AST]]
                     ) -> List[ast.AST]:
        """Argument expressions occupying the donated positions,
        expanding one level of `*args` where args is a local tuple."""
        flat: List[ast.AST] = []
        for a in call.args:
            if isinstance(a, ast.Starred) and isinstance(a.value, ast.Name) \
                    and a.value.id in local_tuples:
                flat.extend(local_tuples[a.value.id])
            else:
                flat.append(a)
        return [flat[p] for p in positions if p < len(flat)]

    def _wrapper_positions(self, fi: FunctionInfo
                           ) -> Optional[Tuple[int, ...]]:
        """Call positions (self stripped) of params this function
        forwards into a donated position of a donating call."""
        params = fi.params
        offset = 1 if params and params[0] == "self" else 0
        tuples = self.local_tuples(fi)
        donated: Set[int] = set()
        for node in _fn_index(fi)[2]:
            pos = self.call_positions(fi, node, tuples)
            if pos is None:
                continue
            for arg in self.donated_args(fi, node, pos, tuples):
                if isinstance(arg, ast.Name) and arg.id in params:
                    donated.add(params.index(arg.id) - offset)
        return tuple(sorted(p for p in donated if p >= 0)) or None

    def inventory(self) -> List[DonationSite]:
        out: List[DonationSite] = []
        seen: Set[Tuple[str, int]] = set()
        for md in self.modules.values():
            for s in md.sites:
                if (s.rel, s.line) in seen:
                    continue
                seen.add((s.rel, s.line))
                out.append(s)
        return sorted(out, key=lambda s: (s.rel, s.line))


def _local_tuples(fn_node: ast.AST) -> Dict[str, List[ast.AST]]:
    """name -> element exprs for locals assigned a tuple literal."""
    out: Dict[str, List[ast.AST]] = {}
    for stmt in ast.walk(fn_node):
        if isinstance(stmt, ast.Assign) and \
                isinstance(stmt.value, ast.Tuple):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = list(stmt.value.elts)
    return out


def donation_inventory(pkg: Package) -> List[DonationSite]:
    """Every donating registration site (entry name + positions). The
    runtime shadow-check asserts the live compile manager's donating
    entries are a subset of this inventory."""
    return _Donations(pkg).inventory()


# -- rule 1+2: use-after-donate and closure escape ------------------------

def _statements_in_order(fn_node: ast.AST) -> List[ast.stmt]:
    """Every statement in the function, OWN body only (nested function
    bodies excluded), in source order."""
    out: List[ast.stmt] = []

    def walk_body(body: List[ast.stmt]) -> None:
        for stmt in body:
            out.append(stmt)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    walk_body(sub)
            for h in getattr(stmt, "handlers", ()):
                walk_body(h.body)

    walk_body(getattr(fn_node, "body", []))
    return sorted(out, key=lambda s: s.lineno)


def _reads_of(stmt: ast.stmt, binding: Tuple[str, str],
              skip_nested: bool = True) -> List[int]:
    kind, name = binding
    lines: List[int] = []

    class V(ast.NodeVisitor):
        def visit_Name(self, node: ast.Name) -> None:
            if kind == "name" and node.id == name \
                    and isinstance(node.ctx, ast.Load):
                lines.append(node.lineno)

        def visit_Attribute(self, node: ast.Attribute) -> None:
            if kind == "attr" and _self_attr(node) == name \
                    and isinstance(node.ctx, ast.Load):
                lines.append(node.lineno)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            if not skip_nested:
                body = node.body if isinstance(node.body, list) \
                    else [node.body]       # Lambda body is an expr
                for s in body:
                    self.visit(s)

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    V().visit(stmt)
    return lines


def _rebinds(stmt: ast.stmt, binding: Tuple[str, str]) -> bool:
    kind, name = binding
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign, ast.For)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.With):
        targets = [i.optional_vars for i in stmt.items
                   if i.optional_vars is not None]
    flat: List[ast.AST] = []
    for t in targets:
        flat.extend(t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t])
    for t in flat:
        if kind == "name" and isinstance(t, ast.Name) and t.id == name:
            return True
        if kind == "attr" and _self_attr(t) == name:
            return True
    return False


def _check_function_donations(pkg: Package, don: _Donations,
                              fi: FunctionInfo,
                              findings: List[Finding]) -> None:
    sf = pkg.files[fi.rel]
    tuples = don.local_tuples(fi)
    stmts = _statements_in_order(fi.node)
    # (binding, donation stmt) pairs in source order. Compound
    # statements are skipped: their leaf statements are in `stmts`
    # individually, so the donating call anchors at its own statement.
    donations: List[Tuple[Tuple[str, str], ast.stmt]] = []
    for stmt in stmts:
        if hasattr(stmt, "body"):
            continue
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            pos = don.call_positions(fi, node, tuples)
            if pos is None:
                continue
            for arg in don.donated_args(fi, node, pos, tuples):
                b = _binding(arg)
                if b is not None:
                    donations.append((b, stmt))

    for binding, dstmt in donations:
        kind, name = binding
        label = name if kind == "name" else f"self.{name}"
        # closure escape: the donated binding captured by any nested
        # function in this function (runs later, buffer gone)
        for node in ast.walk(fi.node):
            if node is not fi.node and \
                    isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                body = node.body if isinstance(node.body, list) \
                    else [ast.Expr(node.body)]
                for s in body:
                    for ln in _reads_of(s, binding, skip_nested=False):
                        if sf.pragma_at(ln, "donate-ok"):
                            continue
                        findings.append(Finding(
                            RULE, fi.rel, ln, fi.qual,
                            f"donate-escape-closure:{label}",
                            f"`{label}` is donated in {fi.name} but "
                            "captured by a nested function — the closure "
                            "runs after the buffer is donated; pass the "
                            "value as an argument or rebind first"))
        # use-after-donate: linear scan past the donating statement
        if _rebinds(dstmt, binding):
            continue    # `x = entry(x, ...)`: rebound at the same stmt
        dead = False
        for stmt in stmts:
            if stmt is dstmt:
                dead = True
                continue
            if not dead or stmt.lineno <= dstmt.lineno:
                continue
            # compound statements: scan only the header expression
            # (test / iter) — their body leaves are in `stmts` already
            if hasattr(stmt, "body"):
                header = getattr(stmt, "test", None) \
                    or getattr(stmt, "iter", None)
                reads = _reads_of(ast.Expr(header), binding) \
                    if header is not None else []
                rebound = _rebinds(stmt, binding)
            else:
                reads = _reads_of(stmt, binding)
                rebound = _rebinds(stmt, binding)
            for ln in reads:
                if sf.pragma_at(ln, "donate-ok"):
                    continue
                findings.append(Finding(
                    RULE, fi.rel, ln, fi.qual,
                    f"use-after-donate:{label}",
                    f"`{label}` was donated into a dispatch above "
                    "(donate_argnums) and read here without a rebind — "
                    "on TPU the buffer now aliases the entry's output"))
            if rebound:
                break


# -- rule 3: device refs escaping into durable payloads -------------------

def _devicey_unlaundered(taint: _DeviceTaint, node: ast.AST) -> bool:
    """Device value NOT passed through a laundering conversion."""
    if isinstance(node, ast.Call):
        fd = dotted(node.func)
        if fd is not None and fd.split(".")[-1] in _LAUNDER_CALLS:
            return False
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_devicey_unlaundered(taint, e) for e in node.elts)
    if isinstance(node, ast.Dict):
        return any(_devicey_unlaundered(taint, v)
                   for v in node.values if v is not None)
    if isinstance(node, ast.ListComp):
        return _devicey_unlaundered(taint, node.elt)
    return taint.is_devicey(node)


def _check_escapes(pkg: Package, fi: FunctionInfo,
                   findings: List[Finding]) -> None:
    sf = pkg.files[fi.rel]
    taint = _DeviceTaint(pkg, fi.rel)
    for stmt in getattr(fi.node, "body", []):
        taint.visit(stmt)

    def flag(node: ast.AST, code: str, msg: str) -> None:
        if sf.pragma_at(node.lineno, "donate-ok"):
            return
        findings.append(Finding(RULE, fi.rel, node.lineno, fi.qual,
                                code, msg))

    is_ckpt = fi.name.split(".")[-1] in _CKPT_METHOD_NAMES
    for node in ast.walk(fi.node):
        # checkpoint payloads: every store into a subscripted dict and
        # every dict-literal value inside a checkpoint_state method
        if is_ckpt:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) \
                            and _devicey_unlaundered(taint, node.value):
                        flag(node, "escape-checkpoint",
                             "device value stored into checkpoint state "
                             "— checkpoints must be device-ref-free "
                             "(np.asarray / device_get first)")
            if isinstance(node, ast.Dict):
                for v in node.values:
                    if v is not None and _devicey_unlaundered(taint, v):
                        flag(v, "escape-checkpoint",
                             "device value in a checkpoint_state payload "
                             "— checkpoints must be device-ref-free")
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == _FLIGHT_DUMP_ATTR and len(node.args) >= 2 \
                    and _devicey_unlaundered(taint, node.args[1]):
                flag(node, "escape-flight",
                     "device value in a flight-recorder dump payload — "
                     "the bundle serializes after the buffer may be "
                     "donated; convert to host data first")
            elif attr in _TELEMETRY_CALLS and len(node.args) >= 2 \
                    and _devicey_unlaundered(taint, node.args[1]):
                flag(node, "escape-telemetry",
                     f"device value passed to {attr}() — telemetry "
                     "payloads outlive the iteration that produced "
                     "them; convert with float()/np.asarray first")


# -- rule 4: trailing-fetch handle drains ---------------------------------

def _pending_fetch_attrs(pkg: Package, methods: List[str]
                         ) -> Dict[str, int]:
    """attr -> first store line, for attrs holding async-copy refs."""
    out: Dict[str, int] = {}
    for q in methods:
        fi = pkg.functions[q]
        # receivers of .copy_to_host_async() + containers they enter
        refs: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "copy_to_host_async" \
                    and isinstance(node.func.value, ast.Name):
                refs.add(node.func.value.id)
        if not refs:
            continue
        def mentions(node: ast.AST) -> bool:
            return any(isinstance(n, ast.Name) and n.id in refs
                       for n in ast.walk(node))
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("append", "add"):
                a = _self_attr(node.func.value)
                if a is not None and any(mentions(x) for x in node.args):
                    out.setdefault(a, node.lineno)
                elif isinstance(node.func.value, ast.Name) \
                        and any(mentions(x) for x in node.args):
                    refs.add(node.func.value.id)
            elif isinstance(node, ast.Assign) and mentions(node.value):
                for t in node.targets:
                    a = _self_attr(t)
                    if a is not None:
                        out.setdefault(a, node.lineno)
    return out


def _resets_attr(pkg: Package, qual: str, attr: str) -> bool:
    fi = pkg.functions[qual]
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Assign) and \
                any(_self_attr(t) == attr for t in node.targets):
            return True
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "clear" \
                and _self_attr(node.func.value) == attr:
            return True
    return False


def _check_fetch_drains(pkg: Package, findings: List[Finding]) -> None:
    classes: Dict[Tuple[str, str], List[str]] = {}
    for qual, fi in pkg.functions.items():
        if fi.cls is not None and "." not in fi.name:
            classes.setdefault((fi.rel, fi.cls), []).append(qual)
    graph = pkg.call_graph()
    for (rel, cls), methods in sorted(classes.items()):
        pending = _pending_fetch_attrs(pkg, sorted(methods))
        if not pending:
            continue
        sf = pkg.files[rel]
        for attr, line in sorted(pending.items()):
            if sf.pragma_at(line, "donate-ok"):
                continue
            drains = [q for q in methods
                      if not q.endswith("__init__")
                      and _resets_attr(pkg, q, attr)]
            if not drains:
                findings.append(Finding(
                    RULE, rel, line, "", f"fetch-no-drain:{cls}.{attr}",
                    f"`self.{attr}` parks copy_to_host_async handles but "
                    f"no method of {cls} ever resets it — in-flight "
                    "fetches need a drain on finish/checkpoint/"
                    "quarantine paths"))
                continue
            # checkpoint discipline: checkpoint_state must reach a drain
            ckpts = [q for q in methods
                     if pkg.functions[q].name in _CKPT_METHOD_NAMES]
            for cq in ckpts:
                reach = pkg.reachable([cq])
                if not any(d in reach for d in drains):
                    findings.append(Finding(
                        RULE, rel, pkg.functions[cq].lineno, cq,
                        f"fetch-ckpt-live:{cls}.{attr}",
                        f"{cls}.checkpoint_state does not drain the "
                        f"in-flight fetch handles in `self.{attr}` — a "
                        "checkpoint must not carry live device refs "
                        "(the _drain_stop_check discipline)"))


# -- pack entry point -----------------------------------------------------

def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    don = _Donations(pkg)
    for qual in sorted(pkg.functions):
        fi = pkg.functions[qual]
        # nested functions are scanned as part of their parent
        if "." in fi.name:
            continue
        _check_function_donations(pkg, don, fi, findings)
        _check_escapes(pkg, fi, findings)
    _check_fetch_drains(pkg, findings)
    # dedupe (closure-escape scan can revisit a line via ast.walk)
    seen: Set[Tuple[str, int, str]] = set()
    out: List[Finding] = []
    for f in findings:
        k = (f.path, f.line, f.code)
        if k in seen:
            continue
        seen.add(k)
        out.append(f)
    return out
