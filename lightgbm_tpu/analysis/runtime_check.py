"""Runtime cross-check for the static sync-point classification.

Two complementary probes, used by the slow test in
tests/test_tpulint.py (and importable for ad-hoc debugging):

- `record_device_gets()` — monkeypatches `jax.device_get` for the
  duration of the context and records the innermost *package* source
  location of every call. Comparing the recorded `(rel, line)` set
  against `static_hot_inventory()` validates that the linter's
  call-graph classification actually covers what runs per iteration.
  (Implicit `np.asarray`/`__array__` transfers can't be patched on
  pybind array types, so the recorder covers the explicit channel; the
  transfer guard below covers the implicit one.)
- `transfer_guard_no_transfers()` — `jax.transfer_guard_device_to_host
  ("disallow")`: any device->host transfer inside the context raises,
  proving a code region is sync-free (or demonstrating a known sync
  site fires, for the positive control).
- `mesh_axis_check()` — builds the runtime mesh (`build_mesh`) and
  asserts every runtime axis name is accounted for by the static
  mesh-axis inventory the collective-axis pack checks against.
- `lifetime_shadow_check()` — every donating entry the live compile
  manager holds must be accounted for by the static donation
  inventory (`lifetime.donation_inventory`): runtime lifetime events
  ⊆ static model, the lifelint analogue of the sync cross-check.
- `capture_donation_warnings()` — collects jax buffer-donation
  warnings so the slow test can promote the real ones to errors while
  tolerating the benign "donation is not implemented on this
  platform" class every CPU dispatch emits.
- `thread_check()` — live `lgbm-*` thread names must be a subset of
  the names the thread-shared-state spawn inventory declares.

jax is imported lazily inside the helpers: the linter core must stay
importable (and fast) without touching jax at all.
"""
from __future__ import annotations

import contextlib
import os
import traceback
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import Package
from . import sync_points

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def package_site(skip_analysis: bool = True,
                 skip_dirs: Tuple[str, ...] = ()
                 ) -> Optional[Tuple[str, int]]:
    """(repo-relative path, line) of the innermost stack frame inside
    the package, skipping this analysis subpackage itself plus any
    subpackage named in `skip_dirs` (the obs tracer passes
    ("analysis", "obs") so its own sync wrappers never self-attribute)."""
    skips = tuple(os.path.join(_PKG_DIR, d) + os.sep
                  for d in (("analysis",) if skip_analysis else ())
                  + tuple(skip_dirs))
    for frame in reversed(traceback.extract_stack()):
        fn = os.path.abspath(frame.filename)
        if not fn.startswith(_PKG_DIR + os.sep):
            continue
        if fn.startswith(skips):
            continue
        # keys match Package rels: repo-root-relative, e.g.
        # "lightgbm_tpu/boosting/gbdt.py"
        rel = os.path.relpath(fn, os.path.dirname(_PKG_DIR))
        return rel, frame.lineno
    return None


@contextlib.contextmanager
def record_device_gets(sites: List[Tuple[str, int]]) -> Iterator[None]:
    """Patch jax.device_get to append each caller's package (rel, line)
    to `sites` (duplicates kept: the count matters for budget checks)."""
    import jax

    real = jax.device_get

    def recording_device_get(*args, **kwargs):
        site = package_site()
        if site is not None:
            sites.append(site)
        return real(*args, **kwargs)

    # install inside the try: if anything goes wrong mid-check the
    # finally still restores the real device_get — a leaked patch would
    # silently corrupt every later test in the process
    try:
        jax.device_get = recording_device_get
        yield
    finally:
        jax.device_get = real


@contextlib.contextmanager
def transfer_guard_no_transfers() -> Iterator[None]:
    """Raise on ANY device->host transfer inside the context."""
    import jax

    with jax.transfer_guard_device_to_host("disallow"):
        yield


def static_hot_inventory(pkg: Optional[Package] = None
                         ) -> Dict[str, Set[int]]:
    """rel -> hot sync-site lines per the static classification."""
    if pkg is None:
        pkg = Package.load()
    return sync_points.hot_site_lines(pkg)


def mesh_axis_check(config=None, pkg: Optional[Package] = None
                    ) -> Dict[str, object]:
    """Compare the meshes the code actually builds against the static
    mesh-axis inventory (mesh_inventory.axis_inventory).

    Builds the runtime mesh via `treelearner.parallel.build_mesh` for
    the given `Config` (default config, i.e. all devices on the "data"
    axis) and reports every runtime axis name the static inventory
    cannot account for. Empty `unaccounted` = the collective-axis
    pack's world model matches reality on this topology.
    """
    from .mesh_inventory import axis_inventory

    if pkg is None:
        pkg = Package.load()
    inv = axis_inventory(pkg)

    from ..config import Config
    from ..treelearner.parallel import build_mesh

    mesh = build_mesh(config if config is not None else Config())
    runtime = [str(a) for a in mesh.axis_names]
    unaccounted = sorted(a for a in runtime if not inv.permits(a))
    return {
        "runtime_axes": runtime,
        "static_axes": sorted(inv.axes),
        "dynamic": inv.dynamic,
        "mesh_sites": sorted(inv.meshes),
        "unaccounted": unaccounted,
    }


# -- lifelint shadow checks (buffer-lifetime / thread-shared-state) -----

# substrings of the benign donation warning jax emits on platforms
# where buffer donation is a no-op (CPU, some GPU paths) — tier-1 runs
# with JAX_PLATFORMS=cpu, so every donating dispatch produces one
_BENIGN_DONATION = ("not implemented", "not supported", "not usable")


@contextlib.contextmanager
def capture_donation_warnings(records: List[str]) -> Iterator[None]:
    """Append the message of every buffer-donation warning raised
    inside the context to `records`. The caller decides severity:
    the slow test treats any message NOT matching `_BENIGN_DONATION`
    (e.g. "some donated buffers were not usable" on a real TPU —
    evidence of a live reference the static model missed) as an
    error, promoting donation warnings the way the ISSUE requires
    without failing the CPU tier."""
    import warnings

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        try:
            yield
        finally:
            for w in caught:
                msg = str(w.message)
                if "donat" in msg.lower():
                    records.append(msg)


def benign_donation_warning(msg: str) -> bool:
    low = msg.lower()
    return any(s in low for s in _BENIGN_DONATION)


def lifetime_shadow_check(pkg: Optional[Package] = None
                          ) -> Dict[str, object]:
    """Runtime-observed donation surface vs the static model.

    Every SharedEntry/JitEntry the live compile manager holds with a
    non-empty `donate_argnums` must correspond to a statically
    discovered donation site (matched by entry name): runtime lifetime
    events ⊆ static inventory. `unaccounted` empty = the
    buffer-lifetime pack's world model covers everything the process
    actually registered."""
    from .lifetime import donation_inventory

    if pkg is None:
        pkg = Package.load()
    static_names = {s.entry_name for s in donation_inventory(pkg)
                    if s.entry_name}

    from ..compile.manager import get_manager

    mgr = get_manager()
    runtime = sorted({e.name for e in mgr.shared.values()
                      if e.donate_argnums})
    unaccounted = sorted(n for n in runtime if n not in static_names)
    return {
        "runtime_donating": runtime,
        "static_entries": sorted(static_names),
        "unaccounted": unaccounted,
    }


def thread_check(pkg: Optional[Package] = None) -> Dict[str, object]:
    """Live `lgbm-*` thread names vs the static spawn inventory.

    A thread the package spawned that the thread-shared-state pack
    does not know about means its shared-attr discipline is checking
    the wrong reachability set — `unaccounted` must stay empty."""
    import threading

    from .threads import thread_names

    if pkg is None:
        pkg = Package.load()
    static = thread_names(pkg)
    live = sorted(t.name for t in threading.enumerate()
                  if t.name.startswith("lgbm-"))
    unaccounted = sorted(n for n in live if n not in static)
    return {
        "live": live,
        "static": sorted(static),
        "unaccounted": unaccounted,
    }
