"""Rule pack: collective-axis.

Device collectives (`lax.psum` / `pmax` / `pmin` / `pmean` /
`all_gather` / `ppermute` / `psum_scatter` / `all_to_all` /
`axis_index`) only work inside a mapped region that binds their axis
name; outside one they raise `NameError: unbound axis` — but only at
trace time on the real topology, which CI never exercises. Three
checks, all against the shared mesh inventory (mesh_inventory.py):

- **axis-unknown** — a literal axis name no mesh in the package
  defines and no partition spec mentions: almost always a typo
  (`"dat"` for `"data"`). Dynamic mesh axes (`f"axis{i}"`) are
  accepted by pattern.
- **unmapped-collective** — the collective's enclosing function is not
  reachable (call graph, over-approximating fallback) from any
  `shard_map`/`pmap` body. Attribute axis arguments
  (`self.psum_axis`) are resolved through package-wide
  `self.<attr> = <const>` assignments; a site whose every resolved
  value is `None` is a guarded no-op and exempt.
- **quantize-contract** — the packed-int32 collective trick
  (`ops/quantize.py`) requires summing the *packed* words:
  `psum(packed_hist_to_pairs(x))` / `psum(unpack_gh(x))` ships the
  unpacked pairs (2x the bytes, f32 on the wire), and
  `pairs_to_packed_hist(psum(...))` / `pack_gh(psum(...))` packs after
  the reduction — both break the contract
  `packed_hist_to_pairs(psum(pairs_to_packed_hist(h), axis))`.

Suppress a deliberate site with `# tpulint: mesh-ok(<reason>)`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, Package, dotted
from .mesh_inventory import (AxisInventory, axis_inventory, mapped_bodies,
                             self_attr_constants)

# collective leaf name -> positional index of the axis-name argument
_COLLECTIVES: Dict[str, int] = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1,
    "all_gather": 1, "ppermute": 1, "psum_scatter": 1, "all_to_all": 1,
    "axis_index": 0,
}

_QUANTIZE_REL = "lightgbm_tpu/ops/quantize.py"
_UNPACKERS = ("packed_hist_to_pairs", "unpack_gh")
_PACKERS = ("pairs_to_packed_hist", "pack_gh")


def _collective_leaf(pkg: Package, rel: str, node: ast.AST) -> Optional[str]:
    """Collective name when `node` is a jax/lax spelling of one."""
    d = dotted(node)
    if d is None:
        return None
    parts = d.split(".")
    leaf = parts[-1]
    if leaf not in _COLLECTIVES:
        return None
    root = parts[0]
    imps = pkg.imports[rel]
    if root in imps.jax or root == "lax" or "lax" in parts[:-1]:
        return leaf
    return None


def _axis_arg(call: ast.Call, leaf: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    pos = _COLLECTIVES[leaf]
    if pos < len(call.args):
        return call.args[pos]
    return None


def _is_quantize_fn(pkg: Package, rel: str, caller, node: ast.AST,
                    names) -> bool:
    """Does `node` name one of ops/quantize.py's `names`?"""
    d = dotted(node)
    if d is None or d.split(".")[-1] not in names:
        return False
    if isinstance(node, (ast.Name, ast.Attribute)):
        if isinstance(node, ast.Call):
            return False
        quals = pkg.resolve_call(rel, caller, node, fallback=False)
        if quals:
            return any(q.split("::")[0].endswith("ops/quantize.py")
                       for q in quals)
    # unresolved but exact leaf-name match: trust the name
    return True


def check(pkg: Package) -> List[Finding]:
    inv: AxisInventory = axis_inventory(pkg)
    roots = mapped_bodies(pkg)
    in_mapped: Set[str] = pkg.reachable(roots) if roots else set()
    attr_consts = self_attr_constants(pkg)
    findings: List[Finding] = []

    for rel in sorted(pkg.files):
        sf = pkg.files[rel]
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _collective_leaf(pkg, rel, node.func)
            caller = pkg.enclosing_function(rel, node)
            qual = caller.qual if caller else ""
            if leaf is None:
                # pack-after-psum: a quantize packer applied to a
                # collective's result (the packer itself is not a
                # collective, so it is handled before the skip)
                if _is_quantize_fn(pkg, rel, caller, node.func, _PACKERS) \
                        and node.args and isinstance(node.args[0], ast.Call):
                    inner = _collective_leaf(pkg, rel, node.args[0].func)
                    if inner in ("psum", "psum_scatter") \
                            and not pkg.files[rel].pragma_at(node.lineno,
                                                             "mesh-ok"):
                        findings.append(Finding(
                            "collective-axis", rel, node.lineno, qual,
                            "pack-after-psum",
                            f"packing the result of lax.{inner} — the "
                            "packed-int32 contract reduces packed words, "
                            "not pairs; pack before the collective"))
                continue

            def emit(code: str, message: str) -> None:
                if sf.pragma_at(node.lineno, "mesh-ok"):
                    return
                findings.append(Finding("collective-axis", rel, node.lineno,
                                        qual, code, message))

            # -- resolve the axis argument -------------------------------
            axis_node = _axis_arg(node, leaf)
            axis_names: List[str] = []
            guarded_none = False
            resolved = False
            if isinstance(axis_node, ast.Constant):
                resolved = True
                if isinstance(axis_node.value, str):
                    axis_names = [axis_node.value]
                elif axis_node.value is None:
                    guarded_none = True
            elif isinstance(axis_node, ast.Attribute) \
                    and isinstance(axis_node.value, ast.Name) \
                    and axis_node.value.id == "self":
                vals = attr_consts.get(axis_node.attr)
                if vals is not None and Ellipsis not in vals:
                    resolved = True
                    axis_names = [v for v in vals if isinstance(v, str)]
                    guarded_none = None in vals
            # tuple/list axes: check each literal element
            elif isinstance(axis_node, (ast.Tuple, ast.List)):
                resolved = all(isinstance(e, ast.Constant)
                               for e in axis_node.elts)
                axis_names = [e.value for e in axis_node.elts
                              if isinstance(e, ast.Constant)
                              and isinstance(e.value, str)]

            # -- axis-unknown -------------------------------------------
            for name in axis_names:
                if not inv.permits(name):
                    emit(f"axis-unknown:{name}",
                         f"lax.{leaf} names axis '{name}' which no Mesh "
                         "or partition spec in the package defines "
                         "(typo?)")

            # -- unmapped-collective ------------------------------------
            # A site whose only resolved axis value is None is guarded
            # (`if self.psum_axis is None: return x`) and exempt; an
            # unresolvable axis argument is skipped, not guessed.
            if resolved and axis_names and qual and qual not in in_mapped:
                emit("unmapped-collective",
                     f"lax.{leaf}(axis='{axis_names[0]}') is not reachable "
                     "from any shard_map/pmap body — unbound axis at "
                     "trace time on a real mesh")
            del guarded_none  # documented above; no separate finding

            # -- quantize-contract --------------------------------------
            if leaf in ("psum", "psum_scatter") and node.args:
                operand = node.args[0]
                if isinstance(operand, ast.Call) and _is_quantize_fn(
                        pkg, rel, caller, operand.func, _UNPACKERS):
                    emit("psum-of-unpacked",
                         "psum of just-unpacked histogram pairs ships 2x "
                         "the bytes; reduce the packed int32 words: "
                         "packed_hist_to_pairs(psum(pairs_to_packed_hist"
                         "(h), axis))")
    return findings
