"""tpulint — project-specific static analysis for lightgbm_tpu.

Nine rule packs over a plain-`ast` model of the package (core.py).
Host-side (PR 4):

- trace-safety      implicit tracer concretization inside jitted code
- sync-point        un-annotated host syncs on the training hot path
- recompile-hazard  jit sites dodging the compile manager, entry
                    signature drift, config fields missing from the
                    AOT signature
- lock-discipline   attributes mutated both under and outside a class's
                    `with self._lock`

Device-side ("meshlint", sharing the same call graph, pragmas, and
baseline):

- collective-axis   collectives outside any shard_map/pmap body, axis
                    typos vs the mesh inventory, packed-psum contract
- kernel-contract   BlockSpec tiling/divisibility, out_shape dtype vs
                    kernel stores, raw memory spaces, bitcast widths
- dtype-flow        narrow-dtype accumulation and dequantize-before-
                    subtract in the quantized histogram pipeline

Lifetime/threading ("lifelint", same infrastructure):

- buffer-lifetime    use-after-donate through the compile-manager
                     entries, device refs escaping into checkpoints /
                     flight bundles / telemetry, undrained
                     copy_to_host_async trailing-fetch handles
- thread-shared-state  thread-spawn inventory + lock discipline by
                     thread-reachability: attrs reachable from more
                     than one thread mutate under a lock or a pragma

Run `python -m lightgbm_tpu.analysis` (exit 0 = clean against the
checked-in baseline), or call `run()` programmatically. The rule
catalogue, pragma syntax, and baseline workflow are documented in
docs/STATIC_ANALYSIS.md.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from .core import (  # noqa: F401  (re-exported API)
    Finding,
    Package,
    PRAGMA_KINDS,
    apply_baseline,
    load_baseline,
    save_baseline,
)
from . import (collective_axis, dtype_flow, kernel_contract, lifetime,
               locks, recompile, sync_points, threads, trace_safety)

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

RULE_PACKS = {
    "trace-safety": trace_safety.check,
    "sync-point": sync_points.check,
    "recompile-hazard": recompile.check,
    "lock-discipline": locks.check,
    "collective-axis": collective_axis.check,
    "kernel-contract": kernel_contract.check,
    "dtype-flow": dtype_flow.check,
    "buffer-lifetime": lifetime.check,
    "thread-shared-state": threads.check,
}

# rule name -> per-pack obs gauge (schema minor 4; lifelint pair minor 12)
_PACK_GAUGES = {
    "collective-axis": "lint.mesh_findings",
    "kernel-contract": "lint.tile_findings",
    "dtype-flow": "lint.dtype_findings",
    "buffer-lifetime": "lint.life_findings",
    "thread-shared-state": "lint.thread_findings",
}


def pragma_hygiene(pkg: Package) -> List[Finding]:
    """Malformed pragmas are findings themselves: unknown kind, or a
    suppression with no reason."""
    out: List[Finding] = []
    for rel in sorted(pkg.files):
        sf = pkg.files[rel]
        for line in sorted(sf.pragmas):
            for p in sf.pragmas[line]:
                if p.kind not in PRAGMA_KINDS:
                    out.append(Finding(
                        "pragma", rel, line, "", f"unknown-kind:{p.kind}",
                        f"unknown tpulint pragma kind '{p.kind}' (valid: "
                        f"{', '.join(PRAGMA_KINDS)})"))
                elif not p.reason:
                    out.append(Finding(
                        "pragma", rel, line, "", f"missing-reason:{p.kind}",
                        f"tpulint pragma '{p.kind}' needs a reason: "
                        f"# tpulint: {p.kind}(<why this is deliberate>)"))
    return out


def collect(pkg: Package,
            rules: Optional[List[str]] = None) -> List[Finding]:
    """All findings from the selected rule packs (default: all four
    plus pragma hygiene), in (path, line) order."""
    findings: List[Finding] = []
    for name, fn in RULE_PACKS.items():
        if rules is None or name in rules:
            findings.extend(fn(pkg))
    if rules is None or "pragma" in (rules or []):
        findings.extend(pragma_hygiene(pkg))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.code))
    return findings


@dataclasses.dataclass
class RunResult:
    new: List[Finding]          # findings NOT absorbed by the baseline
    baselined: List[Finding]    # findings the baseline absorbed
    baseline_size: int          # total allowed occurrences in the baseline
    hot_sync_count: int         # classified hot-loop sync sites (incl.
    #                             annotated ones) — bench.py's metric

    @property
    def ok(self) -> bool:
        return not self.new


def run(root: Optional[str] = None,
        baseline_path: Optional[str] = None,
        rules: Optional[List[str]] = None,
        pkg: Optional[Package] = None) -> RunResult:
    """Analyze the package and apply the baseline.

    Publishes `lint.findings` / `lint.baseline_size` gauges (schema
    minor 3) and the per-pack meshlint gauges `lint.mesh_findings` /
    `lint.tile_findings` / `lint.dtype_findings` (schema minor 4) to
    the active obs registry when one is installed.
    """
    if pkg is None:
        pkg = Package.load(root)
    findings = collect(pkg, rules)
    if baseline_path is None:
        baseline_path = DEFAULT_BASELINE
    baseline = load_baseline(baseline_path) if baseline_path else {}
    new, baselined = apply_baseline(findings, baseline)
    result = RunResult(new, baselined, sum(baseline.values()),
                       sync_points.hot_sync_count(pkg))
    try:  # obs is optional here: the linter must run without jax
        from .. import obs
        reg = obs.active()
        if reg is not None:
            reg.set_gauge("lint.findings", float(len(findings)))
            reg.set_gauge("lint.baseline_size", float(result.baseline_size))
            by_rule: Dict[str, int] = {}
            for f in findings:
                by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
            for rule, gauge in _PACK_GAUGES.items():
                if rules is None or rule in rules:
                    reg.set_gauge(gauge, float(by_rule.get(rule, 0)))
    except Exception:
        pass
    return result


def summary(result: RunResult) -> Dict[str, int]:
    by_rule: Dict[str, int] = {}
    for f in result.new:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return by_rule
