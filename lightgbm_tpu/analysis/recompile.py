"""Rule pack: recompile-hazard.

Four sub-rules protecting the AOT compile cache (PR 2, extended PR 10):

1. **jit-unmanaged** — every `jax.jit` site outside `compile/` must
   route through the compile manager (`get_manager().jit_entry(...)` /
   `shared_entry(...)`) or carry `# tpulint: jit-ok(<reason>)`. Ad-hoc
   jits dodge the recompile counters and the zero-recompile acceptance
   check, which is how signature drift goes unnoticed.
2. **entry-signature** — all registrations of one entry NAME must wrap
   callables with the same positional arity and the same
   static_argnums/static_argnames. Two learners registering
   "serial/split_scan" with different arity would alias distinct traced
   programs under one store key.
3. **config-field** — a Config field read inside traced code must be
   part of the AOT compile signature: reading a field listed in
   `signature.py:_IGNORED_CONFIG_FIELDS` from a traced function means
   two configs differing only in that field replay the SAME serialized
   executable. Also flags stale `_IGNORED_CONFIG_FIELDS` entries that no
   longer name a Config dataclass field.
4. **switch-ladder** — a `lax.switch` whose branch list comes from a
   list comprehension (the capacity-ladder shape: one branch body per
   size bucket). Every branch is cloned into the enclosing HLO, so a
   ladder over kernel bodies multiplies program size by its length —
   the exact bloat PR 10's dynamic-grid kernels removed. Escape with
   `# tpulint: switch-ok(<reason>)` where static branch widths are
   genuinely required (e.g. XLA-sliced fallback paths).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Package, dotted
from .trace_safety import _JitRoots, traced_functions

_SIGNATURE_REL = "lightgbm_tpu/compile/signature.py"
_CONFIG_REL = "lightgbm_tpu/config.py"
_MANAGED_DIR = "lightgbm_tpu/compile/"
_REGISTER_METHODS = ("jit_entry", "shared_entry")
_CONFIG_BASES = ("cfg", "config")


def _jit_call_sites(pkg: Package, rel: str) -> List[ast.Call]:
    """All `jax.jit(...)` / `<alias>.jit(...)` Call nodes in `rel`."""
    imps = pkg.imports[rel]
    out = []
    for node in ast.walk(pkg.files[rel].tree):
        if isinstance(node, ast.Call):
            fd = dotted(node.func)
            if fd is None:
                continue
            parts = fd.split(".")
            if parts[-1] == "jit" and len(parts) > 1 \
                    and parts[0] in imps.jax:
                out.append(node)
    return out


def _decorator_jits(pkg: Package, rel: str) -> List[Tuple[ast.AST, ast.AST]]:
    """(function node, decorator node) for @jax.jit /
    @functools.partial(jax.jit, ...) decorators in `rel`."""
    imps = pkg.imports[rel]

    def is_jit(node: ast.AST) -> bool:
        fd = dotted(node)
        return fd is not None and fd.split(".")[-1] == "jit" \
            and fd.split(".")[0] in imps.jax

    out = []
    for fi in pkg.functions.values():
        if fi.rel != rel:
            continue
        for dec in getattr(fi.node, "decorator_list", []):
            if is_jit(dec):
                out.append((fi.node, dec))
            elif isinstance(dec, ast.Call):
                if is_jit(dec.func):
                    out.append((fi.node, dec))
                else:
                    fd = dotted(dec.func)
                    if fd is not None and fd.split(".")[-1] == "partial" \
                            and dec.args and is_jit(dec.args[0]):
                        out.append((fi.node, dec))
    return out


def _registration_args(pkg: Package, rel: str
                       ) -> List[Tuple[str, ast.Call, ast.AST]]:
    """(entry name, registration call, wrapped expr) for every
    `*.jit_entry("name", expr)` / `*.shared_entry("name", sig, build)`."""
    out = []
    for node in ast.walk(pkg.files[rel].tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REGISTER_METHODS and node.args):
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            name: Optional[str] = first.value
        elif isinstance(first, ast.JoinedStr):
            name = None          # dynamic entry name (f-string)
        else:
            continue
        wrapped = node.args[1] if node.func.attr == "jit_entry" \
            and len(node.args) > 1 else None
        out.append((name, node, wrapped))
    return out


def _routed_names(pkg: Package, rel: str) -> set:
    """Local names handed to a jit_entry()/shared_entry() registration
    anywhere in `rel`. A jit bound to such a name IS manager-routed —
    the builder pattern registers it one statement later."""
    names = set()
    for _name, reg, _w in _registration_args(pkg, rel):
        for arg in reg.args[1:]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
    return names


def _inside_registration(pkg: Package, rel: str, jit_call: ast.Call) -> bool:
    """True when the jit call node is an argument of a jit_entry()
    registration (i.e. routed through the manager)."""
    for _name, reg, _w in _registration_args(pkg, rel):
        for arg in reg.args:
            for sub in ast.walk(arg):
                if sub is jit_call:
                    return True
    return False


def _jit_statics(call: ast.Call) -> Tuple:
    """Canonical (static_argnums, static_argnames) of one jit call."""
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.append(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.append(n.value)
    return (tuple(sorted(nums)), tuple(sorted(names)))


def _wrapped_arity(pkg: Package, rel: str, caller, expr: ast.AST
                   ) -> Optional[Tuple[int, Tuple]]:
    """(positional arity, statics) of the callable a registration wraps,
    unwrapping one jax.jit(...) layer. None when unresolvable."""
    statics: Tuple = ((), ())
    target = expr
    if isinstance(expr, ast.Call):
        fd = dotted(expr.func)
        if fd is not None and fd.split(".")[-1] == "jit" and expr.args:
            statics = _jit_statics(expr)
            target = expr.args[0]
        else:
            return None
    for q in pkg.resolve_call(rel, caller, target):
        fi = pkg.functions.get(q)
        if fi is not None:
            params = [p for p in fi.params if p not in ("self", "cls")]
            return (len(params), statics)
    return None


def _config_fields(pkg: Package) -> Set[str]:
    sf = pkg.files.get(_CONFIG_REL)
    if sf is None:
        return set()
    fields: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
    return fields


def _ignored_fields(pkg: Package) -> Tuple[Set[str], int]:
    """(field set, lineno) of `_IGNORED_CONFIG_FIELDS` in signature.py."""
    sf = pkg.files.get(_SIGNATURE_REL)
    if sf is None:
        return set(), 0
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) \
                        and t.id == "_IGNORED_CONFIG_FIELDS":
                    vals = {n.value for n in ast.walk(node.value)
                            if isinstance(n, ast.Constant)
                            and isinstance(n.value, str)}
                    return vals, node.lineno
    return set(), 0


def _is_config_read(node: ast.Attribute) -> bool:
    """`cfg.<f>` / `config.<f>` / `self.config.<f>` / `self.cfg.<f>`."""
    base = node.value
    if isinstance(base, ast.Name) and base.id in _CONFIG_BASES:
        return True
    if isinstance(base, ast.Attribute) and base.attr in _CONFIG_BASES \
            and isinstance(base.value, ast.Name) \
            and base.value.id == "self":
        return True
    return False


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []

    # (1) unmanaged jax.jit sites
    for rel in sorted(pkg.files):
        if rel.startswith(_MANAGED_DIR):
            continue
        sf = pkg.files[rel]
        routed = _routed_names(pkg, rel)
        for fnode, dec in _decorator_jits(pkg, rel):
            if sf.pragma_at(dec.lineno, "jit-ok") \
                    or sf.pragma_at(fnode.lineno, "jit-ok"):
                continue
            if getattr(fnode, "name", None) in routed:
                continue         # builder pattern: registered below
            fi = pkg.enclosing_function(rel, fnode)
            findings.append(Finding(
                "recompile-hazard", rel, dec.lineno,
                fi.qual if fi is not None else "", "jit-unmanaged",
                "@jax.jit decorator bypasses the compile manager; register "
                "via jit_entry()/shared_entry() or annotate "
                "`# tpulint: jit-ok(<reason>)`"))
        bound_to: Dict[int, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        for sub in ast.walk(node.value):
                            bound_to[id(sub)] = t.id
        for call in _jit_call_sites(pkg, rel):
            if sf.pragma_at(call.lineno, "jit-ok"):
                continue
            if _inside_registration(pkg, rel, call):
                continue
            if bound_to.get(id(call)) in routed:
                continue         # `x = jax.jit(...)` then jit_entry(.., x)
            fi = pkg.enclosing_function(rel, call)
            findings.append(Finding(
                "recompile-hazard", rel, call.lineno,
                fi.qual if fi is not None else "", "jit-unmanaged",
                "jax.jit() call bypasses the compile manager; register via "
                "jit_entry()/shared_entry() or annotate "
                "`# tpulint: jit-ok(<reason>)`"))

    # (2) per-name registration signature consistency
    seen: Dict[str, Tuple[Tuple[int, Tuple], str, int]] = {}
    for rel in sorted(pkg.files):
        for name, reg, wrapped in _registration_args(pkg, rel):
            if wrapped is None or name is None:
                continue
            caller = pkg.enclosing_function(rel, reg)
            sig = _wrapped_arity(pkg, rel, caller, wrapped)
            if sig is None:
                continue
            prev = seen.get(name)
            if prev is None:
                seen[name] = (sig, rel, reg.lineno)
            elif prev[0] != sig:
                fi = pkg.enclosing_function(rel, reg)
                findings.append(Finding(
                    "recompile-hazard", rel, reg.lineno,
                    fi.qual if fi is not None else "",
                    f"entry-signature:{name}",
                    f"entry '{name}' registered with arity/statics {sig} "
                    f"but {prev[1]}:{prev[2]} registered {prev[0]}; one "
                    "store key would alias two traced programs"))

    # (3) ignored-config fields read inside traced code
    cfg_fields = _config_fields(pkg)
    ignored, ignored_line = _ignored_fields(pkg)
    for stale in sorted(ignored - cfg_fields):
        findings.append(Finding(
            "recompile-hazard", _SIGNATURE_REL, ignored_line, "",
            f"stale-ignored:{stale}",
            f"_IGNORED_CONFIG_FIELDS entry '{stale}' is not a Config "
            "field; remove it"))
    traced = set(traced_functions(pkg))
    traced |= set(_JitRoots(pkg).roots)
    for qual in sorted(traced):
        fi = pkg.functions.get(qual)
        if fi is None:
            continue
        sf = pkg.files[fi.rel]
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Attribute) and _is_config_read(node) \
                    and node.attr in ignored and node.attr in cfg_fields:
                if sf.pragma_at(node.lineno, "jit-ok"):
                    continue
                findings.append(Finding(
                    "recompile-hazard", fi.rel, node.lineno, qual,
                    f"config-field:{node.attr}",
                    f"Config.{node.attr} is read inside traced code but "
                    "listed in _IGNORED_CONFIG_FIELDS — two configs "
                    "differing only here would share one executable"))

    # (4) lax.switch branch ladders built by list comprehension
    for rel in sorted(pkg.files):
        sf = pkg.files[rel]
        comp_names: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.ListComp):
                comp_names |= {t.id for t in node.targets
                               if isinstance(t, ast.Name)}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or len(node.args) < 2:
                continue
            fd = dotted(node.func)
            if fd is None:
                continue
            parts = fd.split(".")
            if parts[-1] != "switch" or "lax" not in parts[:-1]:
                continue
            br = node.args[1]
            if not (isinstance(br, ast.ListComp)
                    or (isinstance(br, ast.Name) and br.id in comp_names)):
                continue
            if sf.pragma_at(node.lineno, "switch-ok"):
                continue
            fi = pkg.enclosing_function(rel, node)
            findings.append(Finding(
                "recompile-hazard", rel, node.lineno,
                fi.qual if fi is not None else "", "switch-ladder",
                "lax.switch over a comprehension-built branch ladder "
                "clones every branch body into the enclosing HLO; "
                "parameterize the kernel by runtime size (dynamic grid) "
                "or annotate `# tpulint: switch-ok(<reason>)`"))
    return findings
