"""Rule pack: thread-shared-state ("lifelint", threading half).

The self-healing loop runs real concurrency: the watchdog deadman
(`robust/watchdog.py`), the AOT warmup pool + preload thread
(`compile/warmup.py`), the observability HTTP server
(`obs/httpd.ObsServer`), the bring-up health barrier (`network.py`)
and the flight recorder's cross-thread dump triggers. locks.py checks
that attributes mutated under a class's lock are never mutated outside
it — but says nothing about classes whose methods RUN on more than one
thread without any lock at all.

This pack closes that gap with thread-reachability:

1. **spawn inventory** — every `threading.Thread(target=...)` site,
   every `ThreadPoolExecutor` `.map`/`.submit` dispatch, and HTTP
   handler `do_*` methods (they run on the server's per-request
   threads). The inventory (`spawn_inventory`) also feeds the runtime
   shadow-check: live `lgbm-*` thread names must be a subset of the
   statically declared ones.
2. **shared-attr discipline** — close over the call graph from the
   spawn roots. A method reachable from a spawn site runs off the
   main thread, so for each class: a mutation of an instance
   attribute in a thread-reachable method, or a mutation anywhere of
   an attribute that a thread-reachable method also touches, must
   happen under a `with self.<lock>` — or carry a pragma.

   The closure deliberately does NOT use the package call graph's
   over-approximating simple-name fallback: `manifest.update(...)`
   (a dict) would match `MonotoneState.update` and drag the entire
   single-threaded learner stack into "thread-reachable", burying the
   real concurrency surface under hundreds of false findings. The
   thread graph follows confident resolutions plus a restricted
   fallback: unknown-receiver method calls match only instance
   methods (`def f(self, ...)` inside a class), never names that are
   also builtin container/str/sync-primitive/file verbs, and never
   receivers bound by a non-package import (`json.dump` is not the
   flight recorder's dump).

Exemptions: `__init__` (the object is not shared yet), attributes that
ARE synchronization primitives (`threading.Event` / `Lock` / queues —
self-synchronized by contract), and `# tpulint: thread-ok(<reason>)`
on the mutation line, the line above, or the `class` line (class-level
suppression, for types like the metrics registry whose whole contract
is GIL-atomic single-op writes).
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, FunctionInfo, Package, dotted
from .locks import (_MethodScanner, _Mutation, _class_methods,
                    _lock_attrs, _self_attr)

RULE = "thread-shared-state"

# attribute types that synchronize themselves: assigning/mutating them
# without the class lock is the normal pattern
_SELF_SYNC_CTORS = {"Lock", "RLock", "Event", "Condition", "Semaphore",
                    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue",
                    "LifoQueue", "PriorityQueue"}

# HTTP handler entry points: run on the server's per-request threads
_HANDLER_METHODS = ("do_GET", "do_POST", "do_HEAD", "handle",
                    "log_message")
_HANDLER_BASES = ("BaseHTTPRequestHandler", "SimpleHTTPRequestHandler")

# Attribute names the thread call graph never follows by simple-name
# fallback: verbs of builtin containers/str plus sync-primitive,
# executor, queue, and file-object methods. `d.update(x)` must not
# reach every package method named `update`. Deliberately NOT listed:
# `write` (the jsonl sink is genuinely written from worker threads
# through untyped receivers) and `acquire` (the warmup pool reaches
# the compile manager only through `mgr.acquire`).
_GENERIC_ATTRS = (frozenset(dir(dict)) | frozenset(dir(list))
                  | frozenset(dir(set)) | frozenset(dir(str))
                  | frozenset(dir(tuple)) | frozenset(dir(bytes))
                  | frozenset({
                      "wait", "notify", "notify_all", "is_set", "locked",
                      "release", "start", "submit", "map", "shutdown",
                      "result", "cancel", "done", "add_done_callback",
                      "put", "put_nowait", "get_nowait", "task_done",
                      "qsize", "empty", "full",
                      "close", "flush", "seek", "tell", "read",
                      "readline", "readlines", "writelines", "truncate",
                      "fileno",
                  }))


@dataclasses.dataclass
class SpawnSite:
    """One statically-discovered thread creation."""
    rel: str
    line: int
    func: str                  # enclosing function qual
    kind: str                  # "thread" | "pool" | "handler"
    name: str                  # literal name= kwarg ("" when absent)
    roots: Tuple[str, ...]     # resolved in-package target quals


def _thread_name(call: ast.Call) -> str:
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return ""


def _resolve_target(pkg: Package, rel: str, caller: Optional[FunctionInfo],
                    target: ast.AST) -> Set[str]:
    """Quals a thread-target expression can run: a function reference
    resolves directly; a lambda contributes every call in its body."""
    if isinstance(target, ast.Lambda):
        out: Set[str] = set()
        for node in ast.walk(target.body):
            if isinstance(node, ast.Call):
                out |= pkg.resolve_call(rel, caller, node.func)
        return out
    return pkg.resolve_call(rel, caller, target)


def spawn_inventory(pkg: Package) -> List[SpawnSite]:
    """Every thread-spawn site in the package."""
    sites: List[SpawnSite] = []
    for qual in sorted(pkg.functions):
        fi = pkg.functions[qual]
        if "." in fi.name:
            continue           # nested fns walk with their parent
        # names bound from ThreadPoolExecutor(...) in this function
        pools: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    c = item.context_expr
                    if isinstance(c, ast.Call):
                        fd = dotted(c.func) or ""
                        if fd.split(".")[-1] == "ThreadPoolExecutor" \
                                and isinstance(item.optional_vars,
                                               ast.Name):
                            pools.add(item.optional_vars.id)
            if not isinstance(node, ast.Call):
                continue
            fd = dotted(node.func) or ""
            leaf = fd.split(".")[-1]
            if leaf == "Thread":
                target = next((kw.value for kw in node.keywords
                               if kw.arg == "target"), None)
                roots = _resolve_target(pkg, fi.rel, fi, target) \
                    if target is not None else set()
                sites.append(SpawnSite(
                    fi.rel, node.lineno, qual, "thread",
                    _thread_name(node), tuple(sorted(roots))))
            elif leaf in ("map", "submit") \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in pools and node.args:
                roots = _resolve_target(pkg, fi.rel, fi, node.args[0])
                sites.append(SpawnSite(
                    fi.rel, node.lineno, qual, "pool", "",
                    tuple(sorted(roots))))
    # HTTP handler methods: per-request threads of the obs server
    for qual, fi in sorted(pkg.functions.items()):
        if fi.cls is None or fi.name not in _HANDLER_METHODS:
            continue
        bases = pkg.class_bases.get(fi.rel, {}).get(fi.cls, [])
        if any(b in _HANDLER_BASES for b in bases):
            sites.append(SpawnSite(fi.rel, fi.lineno, qual, "handler",
                                   "", (qual,)))
    return sites


def thread_names(pkg: Package) -> Set[str]:
    """Literal thread names the package spawns (runtime shadow-check:
    live lgbm-* thread names must land in this set)."""
    return {s.name for s in spawn_inventory(pkg) if s.name}


def _external_names(pkg: Package) -> Dict[str, Set[str]]:
    """Per-file names bound by imports that do NOT resolve into the
    package (json, os, pickle, ...). A call through such a receiver is
    external by construction — no simple-name fallback."""
    out: Dict[str, Set[str]] = {}
    for rel, sf in pkg.files.items():
        imps = pkg.imports[rel]
        names: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    names.add(al.asname or al.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for al in node.names:
                    names.add(al.asname or al.name)
        out[rel] = {n for n in names
                    if n not in imps.modules and n not in imps.symbols}
    return out


def _is_instance_method(pkg: Package, qual: str) -> bool:
    fi = pkg.functions[qual]
    if fi.cls is None or "." in fi.name:
        return False
    args = fi.node.args
    return bool(args.args) and args.args[0].arg == "self"


def _thread_call_graph(pkg: Package) -> Dict[str, Set[str]]:
    """Call graph restricted to confident edges plus the narrow
    fallback described in the module docstring: unknown-receiver
    attribute calls match instance methods only, never generic verbs,
    never receivers imported from outside the package."""
    ext = _external_names(pkg)
    graph: Dict[str, Set[str]] = {}
    for qual, fi in pkg.functions.items():
        edges: Set[str] = set()
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            conf = pkg.resolve_call(fi.rel, fi, node.func, fallback=False)
            if conf:
                edges |= conf
                continue
            f = node.func
            if not isinstance(f, ast.Attribute) \
                    or f.attr in _GENERIC_ATTRS:
                continue
            base = f.value
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name) and base.id in ext[fi.rel]:
                continue
            edges |= {q for q in pkg.by_name.get(f.attr, ())
                      if _is_instance_method(pkg, q)}
        graph[qual] = edges
    return graph


def thread_reachable(pkg: Package) -> Set[str]:
    """Quals reachable from ANY spawn-site root: code that can run off
    the main thread."""
    roots: Set[str] = set()
    for s in spawn_inventory(pkg):
        roots |= set(s.roots)
    graph = _thread_call_graph(pkg)
    seen: Set[str] = set()
    stack = [r for r in roots if r in pkg.functions]
    while stack:
        q = stack.pop()
        if q in seen:
            continue
        seen.add(q)
        stack.extend(graph.get(q, ()) - seen)
    return seen


def _self_sync_attrs(pkg: Package, method_quals: List[str]) -> Set[str]:
    """Attrs assigned a synchronization-primitive constructor."""
    attrs: Set[str] = set()
    for q in method_quals:
        fi = pkg.functions[q]
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                fd = dotted(node.value.func)
                if fd is not None \
                        and fd.split(".")[-1] in _SELF_SYNC_CTORS:
                    for t in node.targets:
                        a = _self_attr(t)
                        if a is not None:
                            attrs.add(a)
    return attrs


class _AccessScanner(_MethodScanner):
    """locks.py's mutation scanner, plus self-attr READ tracking."""

    def __init__(self, lock_attrs: Set[str], method_qual: str) -> None:
        super().__init__(lock_attrs, method_qual)
        self.reads: Set[str] = set()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        a = _self_attr(node)
        if a is not None and isinstance(node.ctx, ast.Load) \
                and a not in self.lock_attrs:
            self.reads.add(a)
        self.generic_visit(node)


def _class_pragma(pkg: Package, rel: str, cls: str) -> bool:
    """Class-level `# tpulint: thread-ok(...)` on the class line."""
    sf = pkg.files[rel]
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            return sf.pragma_at(node.lineno, "thread-ok") is not None
    return False


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    hot = thread_reachable(pkg)
    for (rel, cls), methods in sorted(_class_methods(pkg).items()):
        thread_methods = {q for q in methods if q in hot}
        if not thread_methods:
            continue
        if _class_pragma(pkg, rel, cls):
            continue
        sf = pkg.files[rel]
        lock_attrs = _lock_attrs(pkg, methods)
        sync_attrs = _self_sync_attrs(pkg, methods)
        mutations: List[_Mutation] = []
        touched_by_thread: Set[str] = set()   # attrs a thread can see
        for q in sorted(methods):
            fi = pkg.functions[q]
            scan = _AccessScanner(lock_attrs, q)
            for stmt in fi.node.body:
                scan.visit(stmt)
            mutations.extend(scan.mutations)
            if q in thread_methods and not q.endswith(".__init__"):
                touched_by_thread |= scan.reads
                touched_by_thread |= {m.attr for m in scan.mutations}
        for m in mutations:
            if m.attr in sync_attrs or m.under_lock:
                continue
            if m.method.endswith(".__init__"):
                continue
            # shared = mutated on a worker thread, or mutated anywhere
            # while a worker-thread method also touches it
            on_thread = m.method in thread_methods
            if not on_thread and m.attr not in touched_by_thread:
                continue
            if sf.pragma_at(m.line, "thread-ok"):
                continue
            where = "on a spawned thread" if on_thread \
                else "on the main thread while a spawned thread reads it"
            findings.append(Finding(
                RULE, rel, m.line, m.method,
                f"{cls}.{m.attr}:{m.kind}",
                f"`self.{m.attr}` is mutated {where} "
                f"({m.kind.replace('call:', '.')}) without holding a "
                f"lock — {cls} methods run on more than one thread; "
                "guard with the class lock or annotate "
                "`# tpulint: thread-ok(<reason>)`"))
    return findings
