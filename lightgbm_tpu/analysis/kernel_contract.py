"""Rule pack: kernel-contract.

Per-`pallas_call` contract checks that fail only at Mosaic lowering
time on a real TPU (or worse, silently pad):

- **tile-lane / tile-sublane** — literal BlockSpec dims must respect
  the TPU register tiling: last dim a multiple of 128 (the lane
  width), second-to-last a multiple of 8 (f32/i32 sublane; int16/bf16
  need 16, int8 32 — the pack checks the weakest bound it can prove,
  see docs/STATIC_ANALYSIS.md for the table). Non-literal dims are
  trusted: the repo sizes blocks from `config.tpu_*` knobs that the
  runtime validates.
- **block-divisibility** — when `out_shape` and the out `BlockSpec`
  both carry literal dim tuples of the same rank, every shape dim must
  divide evenly by its block dim (Pallas pads the remainder block and
  the kernel reads garbage lanes).
- **out-dtype** — the dtype a kernel body stores into its out ref
  (`out_ref[...] = x.astype(...)`) must match the `ShapeDtypeStruct`
  dtype declared in `out_shape`; a mismatch means an implicit convert
  on every store.
- **memspace** — raw `pltpu.HBM` / `pltpu.ANY` / `pltpu.TPUMemorySpace`
  references outside `utils/compat.py`: the attribute moved across jax
  releases, so all memory-space annotations go through
  `compat.pallas_hbm_space`. (`SMEM`/`VMEM` never moved and are fine.)
- **bitcast-width** — `lax.bitcast_convert_type(x, T)` where `x`'s
  dtype is statically known (an `.astype(S)` wrap or a prior
  bitcast/astype assignment in the same function) and `S`/`T` have
  different bit widths: the result grows/splits a trailing dim, which
  is occasionally intended (the packed-plane read) but never obvious.

Suppress a deliberate site with `# tpulint: tile-ok(<reason>)`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Package, dotted

_LANE = 128
_SUBLANE = 8

_DTYPE_BITS = {
    "float64": 64, "int64": 64, "uint64": 64,
    "float32": 32, "int32": 32, "uint32": 32,
    "float16": 16, "bfloat16": 16, "int16": 16, "uint16": 16,
    "int8": 8, "uint8": 8, "bool_": 8, "float8_e4m3fn": 8,
    "float8_e5m2": 8,
}

_RAW_MEMSPACES = ("HBM", "ANY", "TPUMemorySpace")
_COMPAT_REL = "lightgbm_tpu/utils/compat.py"


def _pallas_aliases(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(pl aliases, pltpu aliases) — pallas imports are function-local
    in this repo, so scan the whole tree, not just module level."""
    pl_names: Set[str] = set()
    pltpu_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.level == 0:
            if node.module == "jax.experimental":
                for al in node.names:
                    if al.name == "pallas":
                        pl_names.add(al.asname or "pallas")
            elif node.module == "jax.experimental.pallas":
                for al in node.names:
                    if al.name == "tpu":
                        pltpu_names.add(al.asname or "tpu")
        elif isinstance(node, ast.Import):
            for al in node.names:
                if al.name == "jax.experimental.pallas" and al.asname:
                    pl_names.add(al.asname)
                elif al.name == "jax.experimental.pallas.tpu" and al.asname:
                    pltpu_names.add(al.asname)
    return pl_names, pltpu_names


def _dtype_leaf(node: Optional[ast.AST]) -> Optional[str]:
    """'float32' from `jnp.float32` / `np.float32` / `"float32"`."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _DTYPE_BITS else None
    d = dotted(node)
    if d is not None:
        leaf = d.split(".")[-1]
        if leaf in _DTYPE_BITS:
            return leaf
    return None


def _literal_dims(node: Optional[ast.AST]) -> Optional[List[Optional[int]]]:
    """Dim list from a tuple/list literal; non-literal dims -> None
    entries. Returns None when `node` isn't a tuple/list at all."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out: List[Optional[int]] = []
    for e in node.elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            out.append(e.value)
        else:
            out.append(None)
    return out


def _blockspec_dims(call: ast.Call) -> Optional[List[Optional[int]]]:
    """The block-shape tuple of a BlockSpec(...) call (first positional
    arg or block_shape= kwarg)."""
    spec = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "block_shape":
            spec = kw.value
    return _literal_dims(spec)


class _FileChecker:
    def __init__(self, pkg: Package, rel: str,
                 findings: List[Finding]) -> None:
        self.pkg = pkg
        self.rel = rel
        self.sf = pkg.files[rel]
        self.findings = findings
        self.pl, self.pltpu = _pallas_aliases(self.sf.tree)

    def _emit(self, node: ast.AST, code: str, message: str) -> None:
        if self.sf.pragma_at(node.lineno, "tile-ok"):
            return
        caller = self.pkg.enclosing_function(self.rel, node)
        self.findings.append(Finding(
            "kernel-contract", self.rel, node.lineno,
            caller.qual if caller else "", code, message))

    # -- tiling ----------------------------------------------------------
    def check_blockspec(self, call: ast.Call) -> None:
        dims = _blockspec_dims(call)
        if not dims:
            return
        lane = dims[-1]
        if lane is not None and lane % _LANE != 0:
            self._emit(call, f"tile-lane:{lane}",
                       f"BlockSpec last dim {lane} is not a multiple of "
                       f"the TPU lane width {_LANE}; the block pads to "
                       f"{_LANE} lanes and wastes the register file")
        if len(dims) >= 2:
            sub = dims[-2]
            if sub is not None and sub % _SUBLANE != 0:
                self._emit(call, f"tile-sublane:{sub}",
                           f"BlockSpec sublane dim {sub} is not a multiple "
                           f"of {_SUBLANE} (f32 min tile; int16/bf16 need "
                           "16, int8 32)")

    # -- pallas_call: divisibility + out dtype ---------------------------
    def check_pallas_call(self, call: ast.Call) -> None:
        out_shape_kw = out_specs_kw = None
        for kw in call.keywords:
            if kw.arg == "out_shape":
                out_shape_kw = kw.value
            elif kw.arg == "out_specs":
                out_specs_kw = kw.value
        sds_calls = [n for n in ast.walk(out_shape_kw)
                     if isinstance(n, ast.Call)
                     and (dotted(n.func) or "").split(".")[-1]
                     == "ShapeDtypeStruct"] if out_shape_kw else []
        if out_specs_kw is not None and len(sds_calls) == 1:
            spec_calls = [n for n in ast.walk(out_specs_kw)
                          if isinstance(n, ast.Call)
                          and (dotted(n.func) or "").split(".")[-1]
                          == "BlockSpec"]
            if len(spec_calls) == 1:
                shape = _literal_dims(sds_calls[0].args[0]
                                      if sds_calls[0].args else None)
                block = _blockspec_dims(spec_calls[0])
                if shape and block and len(shape) == len(block):
                    for i, (s, b) in enumerate(zip(shape, block)):
                        if s is not None and b is not None and b > 0 \
                                and s % b != 0:
                            self._emit(
                                spec_calls[0], f"block-divisibility:{i}",
                                f"out dim {i} = {s} is not divisible by "
                                f"its block dim {b}; Pallas pads the last "
                                "block and the kernel sees garbage rows")
        # out-dtype: declared ShapeDtypeStruct dtype vs kernel stores
        if len(sds_calls) == 1:
            decl = _dtype_leaf(
                sds_calls[0].args[1] if len(sds_calls[0].args) > 1 else
                next((kw.value for kw in sds_calls[0].keywords
                      if kw.arg == "dtype"), None))
            if decl is not None:
                self._check_kernel_stores(call, decl)

    def _kernel_quals(self, call: ast.Call) -> Set[str]:
        target = call.args[0] if call.args else None
        if isinstance(target, ast.Call):  # partial(kernel, ...)
            fd = dotted(target.func)
            if fd is not None and fd.split(".")[-1] == "partial" \
                    and target.args:
                target = target.args[0]
        if target is None or isinstance(target, ast.Lambda):
            return set()
        caller = self.pkg.enclosing_function(self.rel, call)
        return self.pkg.resolve_call(self.rel, caller, target,
                                     fallback=False)

    def _check_kernel_stores(self, call: ast.Call, decl: str) -> None:
        for q in self._kernel_quals(call):
            fi = self.pkg.functions.get(q)
            if fi is None:
                continue
            out_params = {p for p in fi.params
                          if "out" in p or p.startswith("o_")}
            if not out_params:
                continue
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Subscript)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id in out_params):
                    continue
                v = node.value
                if isinstance(v, ast.Call) \
                        and isinstance(v.func, ast.Attribute) \
                        and v.func.attr == "astype" and v.args:
                    stored = _dtype_leaf(v.args[0])
                    if stored is not None and stored != decl:
                        sf = self.pkg.files[fi.rel]
                        if sf.pragma_at(node.lineno, "tile-ok"):
                            continue
                        self.findings.append(Finding(
                            "kernel-contract", fi.rel, node.lineno, q,
                            f"out-dtype:{stored}-vs-{decl}",
                            f"kernel stores {stored} into an out ref "
                            f"declared {decl} in out_shape — implicit "
                            "convert on every store"))

    # -- memory space ----------------------------------------------------
    def check_memspace(self, node: ast.Attribute) -> None:
        if self.rel == _COMPAT_REL:
            return
        if node.attr in _RAW_MEMSPACES \
                and isinstance(node.value, ast.Name) \
                and node.value.id in self.pltpu:
            self._emit(node, f"memspace:{node.attr}",
                       f"raw pltpu.{node.attr} — the attribute moved "
                       "across jax releases; use "
                       "utils.compat.pallas_hbm_space(pltpu)")

    # -- bitcast width ---------------------------------------------------
    def _source_dtype(self, expr: ast.AST,
                      fn_node: Optional[ast.AST],
                      before_line: int) -> Optional[str]:
        """dtype of `expr` when statically evident: an `.astype(S)` /
        bitcast wrap, or a Name whose latest assignment before
        `before_line` in the enclosing function is such a wrap."""
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr == "astype" and expr.args:
                return _dtype_leaf(expr.args[0])
            fd = dotted(expr.func)
            if fd is not None \
                    and fd.split(".")[-1] == "bitcast_convert_type" \
                    and len(expr.args) > 1:
                return _dtype_leaf(expr.args[1])
            return None
        if isinstance(expr, ast.Name) and fn_node is not None:
            best: Optional[Tuple[int, Optional[str]]] = None
            for n in ast.walk(fn_node):
                if isinstance(n, ast.Assign) and n.lineno < before_line \
                        and any(isinstance(t, ast.Name) and t.id == expr.id
                                for t in n.targets):
                    dt = self._source_dtype(n.value, None, before_line)
                    if best is None or n.lineno > best[0]:
                        best = (n.lineno, dt)
            return best[1] if best else None
        return None

    def check_bitcast(self, call: ast.Call) -> None:
        if len(call.args) < 2:
            return
        dst = _dtype_leaf(call.args[1])
        if dst is None:
            return
        caller = self.pkg.enclosing_function(self.rel, call)
        src = self._source_dtype(call.args[0],
                                 caller.node if caller else None,
                                 call.lineno)
        if src is None:
            return
        if _DTYPE_BITS[src] != _DTYPE_BITS[dst]:
            self._emit(call, f"bitcast-width:{src}->{dst}",
                       f"bitcast_convert_type {src} ({_DTYPE_BITS[src]}b) "
                       f"-> {dst} ({_DTYPE_BITS[dst]}b) changes the bit "
                       "width: the result gains/splits a trailing dim")

    # -- driver ----------------------------------------------------------
    def run(self) -> None:
        for node in ast.walk(self.sf.tree):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                leaf = d.split(".")[-1] if d else None
                if leaf == "BlockSpec":
                    self.check_blockspec(node)
                elif leaf == "pallas_call":
                    self.check_pallas_call(node)
                elif leaf == "bitcast_convert_type":
                    self.check_bitcast(node)
            elif isinstance(node, ast.Attribute):
                self.check_memspace(node)


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    for rel in sorted(pkg.files):
        _FileChecker(pkg, rel, findings).run()
    return findings
