"""Rule pack: lock-discipline.

For every class that owns a `threading.Lock`/`RLock` (an attribute
assigned `threading.Lock()` in any of its methods), find instance
attributes that are mutated at least once inside a `with self.<lock>:`
block — those are the lock-protected ones — and flag every OTHER
mutation of the same attribute that happens outside the lock.

This is exactly the PR 2 review bug class: `CompileManager.executables`
was LRU-maintained under `_lock` in `_remember` but also written
directly from the exec-reject fallback path.

Scope rules:
- `__init__` mutations are exempt (the object isn't shared yet).
- Mutations counted: `self.a = ...`, `self.a += ...`, `self.a[k] = ...`,
  `del self.a[...]`, and mutating method calls
  (`self.a.append/pop/clear/update/...`).
- A nested function defined inside a method is analyzed as NOT holding
  the enclosing `with` lock — it typically runs later on another thread
  (warmup closures), which is the dangerous case.
- Suppress with `# tpulint: lock-ok(<reason>)`.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Package, dotted

_LOCK_CTORS = {"Lock", "RLock"}
_MUTATING_METHODS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "setdefault", "move_to_end", "add", "remove", "discard", "sort",
    "reverse", "appendleft", "popleft",
}


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is `self.x`."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


@dataclasses.dataclass
class _Mutation:
    attr: str
    line: int
    under_lock: bool
    method: str            # function qual
    kind: str              # "assign" | "call:<name>" | "del"


class _MethodScanner(ast.NodeVisitor):
    def __init__(self, lock_attrs: Set[str], method_qual: str) -> None:
        self.lock_attrs = lock_attrs
        self.method = method_qual
        self.depth = 0
        self.mutations: List[_Mutation] = []

    # -- lock context ----------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        holds = any(_self_attr(item.context_expr) in self.lock_attrs
                    for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
        if holds:
            self.depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if holds:
            self.depth -= 1

    def visit_FunctionDef(self, node) -> None:
        # a closure runs later, possibly on another thread: the lock the
        # enclosing method holds is NOT held when it executes
        saved = self.depth
        self.depth = 0
        for stmt in node.body:
            self.visit(stmt)
        self.depth = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- mutations -------------------------------------------------------
    def _record(self, attr: Optional[str], node: ast.AST, kind: str) -> None:
        if attr is None or attr in self.lock_attrs:
            return
        self.mutations.append(_Mutation(attr, node.lineno, self.depth > 0,
                                        self.method, kind))

    def _target_attr(self, target: ast.AST) -> Optional[str]:
        if isinstance(target, ast.Subscript):
            return self._target_attr(target.value)
        return _self_attr(target)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record(self._target_attr(t), node, "assign")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(self._target_attr(node.target), node, "assign")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record(self._target_attr(node.target), node, "assign")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._record(self._target_attr(t), node, "del")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATING_METHODS:
            self._record(_self_attr(node.func.value), node,
                         f"call:{node.func.attr}")
        self.generic_visit(node)


def _class_methods(pkg: Package) -> Dict[Tuple[str, str], List[str]]:
    """(rel, class) -> [method quals] (top-level methods only)."""
    out: Dict[Tuple[str, str], List[str]] = {}
    for qual, fi in pkg.functions.items():
        if fi.cls is not None and "." not in fi.name:
            out.setdefault((fi.rel, fi.cls), []).append(qual)
    return out


def _lock_attrs(pkg: Package, method_quals: List[str]) -> Set[str]:
    attrs: Set[str] = set()
    for q in method_quals:
        fi = pkg.functions[q]
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                fd = dotted(node.value.func)
                if fd is not None and fd.split(".")[-1] in _LOCK_CTORS:
                    for t in node.targets:
                        a = _self_attr(t)
                        if a is not None:
                            attrs.add(a)
    return attrs


def check(pkg: Package) -> List[Finding]:
    findings: List[Finding] = []
    for (rel, cls), methods in sorted(_class_methods(pkg).items()):
        lock_attrs = _lock_attrs(pkg, methods)
        if not lock_attrs:
            continue
        sf = pkg.files[rel]
        mutations: List[_Mutation] = []
        for q in sorted(methods):
            fi = pkg.functions[q]
            scan = _MethodScanner(lock_attrs, q)
            for stmt in fi.node.body:
                scan.visit(stmt)
            mutations.extend(scan.mutations)
        guarded = {m.attr for m in mutations if m.under_lock}
        for m in mutations:
            if m.attr not in guarded or m.under_lock:
                continue
            if m.method.endswith(".__init__"):
                continue
            if sf.pragma_at(m.line, "lock-ok"):
                continue
            findings.append(Finding(
                "lock-discipline", rel, m.line, m.method,
                f"{cls}.{m.attr}:{m.kind}",
                f"`self.{m.attr}` is mutated under `with self.<lock>` "
                f"elsewhere in {cls} but {m.kind.replace('call:', '.')} "
                "here runs without the lock"))
    return findings
