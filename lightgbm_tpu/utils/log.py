"""Logging for lightgbm_tpu.

TPU-native equivalent of the reference's ``Log`` utility
(reference: include/LightGBM/utils/log.h:81-110): leveled logging with a
registerable callback (used by the Python-facing API the same way the
reference routes C++ logs through a ctypes callback, python-package
lightgbm/basic.py:24).
"""
from __future__ import annotations

import sys
from typing import Callable, Optional

# config-level verbosity, reference scale (src/io/config.cpp:234-242):
# <0: fatal only, 0: warning+error, 1: info (default), >=2: debug
_verbosity = 1
_callback: Optional[Callable[[str], None]] = None


class LightGBMError(Exception):
    """Error raised by lightgbm_tpu (mirrors the reference's LightGBMError)."""


def set_verbosity(level: int) -> None:
    """<0: fatal only, 0: warning, 1: info, >=2: debug (reference scale)."""
    global _verbosity
    _verbosity = level


def register_log_callback(cb: Optional[Callable[[str], None]]) -> None:
    global _callback
    _callback = cb


def _emit(msg: str) -> None:
    if _callback is not None:
        # a raising user callback must not kill training mid-iteration;
        # fall back to stderr so the line is not lost
        try:
            _callback(msg + "\n")
            return
        except Exception as exc:
            sys.stderr.write(
                f"[LightGBM-TPU] [Warning] log callback raised {exc!r}; "
                "falling back to stderr\n")
    sys.stderr.write(msg + "\n")


def trace(msg: str, *args) -> None:
    """Highest-volume level (verbosity >= 3): per-kernel / per-span
    detail from the obs layer."""
    if _verbosity >= 3:
        _emit("[LightGBM-TPU] [Trace] " + (msg % args if args else msg))


def debug(msg: str, *args) -> None:
    if _verbosity >= 2:
        _emit("[LightGBM-TPU] [Debug] " + (msg % args if args else msg))


def info(msg: str, *args) -> None:
    if _verbosity >= 1:
        _emit("[LightGBM-TPU] [Info] " + (msg % args if args else msg))


def warning(msg: str, *args) -> None:
    if _verbosity >= 0:
        _emit("[LightGBM-TPU] [Warning] " + (msg % args if args else msg))


def fatal(msg: str, *args) -> None:
    text = msg % args if args else msg
    _emit("[LightGBM-TPU] [Fatal] " + text)
    raise LightGBMError(text)
