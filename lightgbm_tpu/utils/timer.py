"""Named-scope timing with an aggregated global table.

Equivalent of the reference's Timer/FunctionTimer + global_timer
(reference: include/LightGBM/utils/common.h:1054-1138 — RAII scopes
around every hot function, aggregated by name, printed at exit when
built with -DUSE_TIMETAG). Here the same scopes also emit
jax.profiler.TraceAnnotation ranges so device traces line up with the
host-side phase table.
"""
from __future__ import annotations

import atexit
import contextlib
import functools
import os
import time
from collections import defaultdict
from typing import Dict, Optional

from . import log


def env_enabled() -> bool:
    """Current LGBM_TPU_TIMETAG state (read per call, not at import —
    tests and late os.environ writes see the live value)."""
    return os.environ.get("LGBM_TPU_TIMETAG", "") not in ("", "0", "false")


class Timer:
    def __init__(self, enabled: Optional[bool] = None) -> None:
        self.acc: Dict[str, float] = defaultdict(float)
        self.cnt: Dict[str, int] = defaultdict(int)
        self.enabled = env_enabled() if enabled is None else bool(enabled)

    def set_enabled(self, on: bool) -> None:
        self.enabled = bool(on)

    @contextlib.contextmanager
    def scope(self, name: str):
        if not self.enabled:
            yield
            return
        try:
            import jax.profiler
            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
        except Exception:
            ann = None
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.acc[name] += time.perf_counter() - t0
            self.cnt[name] += 1
            if ann is not None:
                ann.__exit__(None, None, None)

    def report(self) -> str:
        lines = ["LightGBM-TPU timer table:"]
        for name in sorted(self.acc, key=lambda k: -self.acc[k]):
            lines.append(f"  {name}: {self.acc[name]:.3f}s over {self.cnt[name]} calls")
        return "\n".join(lines)

    def reset(self) -> None:
        self.acc.clear()
        self.cnt.clear()

    def print_at_exit(self) -> None:
        if self.enabled and self.acc:
            log.info("%s", self.report())


global_timer = Timer()
atexit.register(global_timer.print_at_exit)


def set_enabled(on: bool) -> None:
    """Toggle the global timer at runtime (the
    `lgb.train(params={"timetag": True})` path — no reimport needed)."""
    global_timer.set_enabled(on)


def function_timer(name: str):
    """Decorator form (reference Common::FunctionTimer)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with global_timer.scope(name):
                return fn(*args, **kwargs)
        return wrapper
    return deco
