"""JAX version compatibility shims.

The code targets the current jax API surface; this module backfills the
pieces that moved between releases so the same source runs on the
container's pinned jax as well:

- `shard_map`: promoted out of `jax.experimental` (and its `check_rep`
  kwarg renamed to `check_vma`) in newer releases. Callers always use
  the new name/kwarg; the shim translates when only the experimental
  API exists.
- `pallas_hbm_space()`: `pltpu.HBM` replaced the older
  `TPUMemorySpace.ANY` spelling for unblocked HBM operands in manual-DMA
  kernels.
"""
from __future__ import annotations

import functools

try:                                    # jax >= 0.6: public API, check_vma
    from jax import shard_map as _new_shard_map

    def shard_map(f=None, **kw):
        if f is None:
            return functools.partial(shard_map, **kw)
        return _new_shard_map(f, **kw)

except ImportError:                     # jax 0.4.x: experimental, check_rep
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f=None, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        if f is None:
            return functools.partial(shard_map, **kw)
        return _old_shard_map(f, **kw)


def pallas_hbm_space(pltpu):
    """Unblocked-HBM memory space constant for `pl.BlockSpec`, for
    whichever spelling this jax provides."""
    hbm = getattr(pltpu, "HBM", None)
    return hbm if hbm is not None else pltpu.ANY
