"""Process-global AOT compile manager.

The manager owns every jit entry point in the stack. Learners register
entries instead of calling `jax.jit` ad hoc, which buys three things:

- **Sharing**: entries are deduplicated by compile-signature digest, so
  a second grower built for a same-bucket dataset dispatches through the
  first grower's executable — zero retraces, zero recompiles.
- **Durability**: executables compiled through `.lower().compile()` are
  serialized into the `ExecutableStore`; later processes deserialize
  instead of compiling.
- **Warmup**: each shared entry can carry abstract call specs
  (ShapeDtypeStruct avals), letting warmup threads compile ahead of the
  first training iteration (compile/warmup.py).

Dispatch order per (entry, concrete shapes): in-memory executable →
store deserialize → lower+compile (+ serialize) → plain jit fallback.
Every transition is counted in `CompileManager.stats` and mirrored to
the active obs registry under `compile.*` counters and the
"compile"/"aot_load"/"aot_serialize" phase timers.

Thread-safety: per-key locks serialize duplicate compiles (a warmup
thread and the training thread asking for the same key compile once); a
single trace lock serializes `.lower()` calls because entry builders may
temporarily bind instance state (fused.py `_bind_tables`).
"""
from __future__ import annotations

import atexit
import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from ..utils import log
from . import signature as S
from .store import (CorruptBlobError, ExecutableStore, min_compile_s,
                    store_enabled)

_FALLBACK = object()  # dispatch marker: this key uses plain jit forever


def is_executable(exe: Any) -> bool:
    """True only for a real compiled executable — not None and not the
    plain-jit fallback marker (which means the compile FAILED)."""
    return exe is not None and exe is not _FALLBACK

_MAX_SHARED_ENTRIES = 32   # LRU cap: entries close over growers/datasets
_MAX_EXECUTABLES = 128


def _count_donated_bytes(donate_argnums: Tuple[int, ...],
                         args: Tuple[Any, ...]) -> None:
    """pipeline.donated_bytes: HBM handed back to the allocator by a
    donating dispatch. Reads only .nbytes metadata — never the buffer
    contents — so it is safe on arguments about to be donated (and on
    already-deleted leaves, which may raise from their accessors)."""
    from .. import obs
    reg = obs.active()
    if reg is None:
        return
    total = 0
    for i in donate_argnums:
        if i < len(args):
            for leaf in jax.tree_util.tree_leaves(args[i]):
                try:
                    total += int(getattr(leaf, "nbytes", 0) or 0)
                except Exception:
                    continue
    if total:
        reg.inc("pipeline.donated_bytes", total)


def _aot_supported() -> bool:
    try:
        from jax.experimental import serialize_executable  # noqa: F401
        return True
    except Exception:
        return False


class SharedEntry:
    """One named jit entry point, shareable across learner instances
    whose compile signatures match. Calling it dispatches AOT-first."""

    def __init__(self, manager: "CompileManager", name: str,
                 digest: str, build: Callable[[], Callable],
                 donate_argnums: Tuple[int, ...] = (),
                 store: bool = True) -> None:
        self.manager = manager
        self.name = name
        self.digest = digest
        self.donate_argnums = tuple(donate_argnums)
        # store=False: compile + share in-memory, but never persist —
        # used when the signature fell back to a per-instance uid
        # (io/dataset.py trace_signature), which would pollute the
        # on-disk store with keys no later process can ever hit
        self.store = bool(store)
        self._build = build
        self._jfn: Optional[Callable] = None
        # guards _jfn / _key_cache / specs: entries are shared across
        # learner instances and warmed up from worker threads
        self._lock = threading.RLock()
        self._key_cache: Dict[Tuple, str] = {}
        # warmup specs: list of (args_pytree_of_avals, statics_dict)
        self.specs: List[Tuple[Any, Dict[str, Any]]] = []

    def jit_fn(self) -> Callable:
        with self._lock:
            if self._jfn is None:
                self._jfn = self._build()
            return self._jfn

    def add_spec(self, args: Any, statics: Optional[Dict[str, Any]] = None
                 ) -> None:
        statics = dict(statics or {})
        with self._lock:
            key = self.key_for(args, statics)
            if all(self.key_for(a, s) != key for a, s in self.specs):
                self.specs.append((args, statics))

    def key_for(self, args: Any, statics: Dict[str, Any]) -> str:
        ss = S.shape_signature(args, statics)
        with self._lock:
            key = self._key_cache.get(ss)
            if key is None:
                key = S.cache_key(self.digest, ss)
                self._key_cache[ss] = key
        return key

    def __call__(self, *args: Any, **statics: Any) -> Any:
        mgr = self.manager
        if self.donate_argnums:
            _count_donated_bytes(self.donate_argnums, args)
        if not mgr.aot_enabled:
            return self.jit_fn()(*args, **statics)
        key = self.key_for(args, statics)
        exe = mgr.executables.get(key)
        if exe is None:
            exe = mgr.acquire(self, key, args, statics)
        else:
            mgr.count("cache_hits")
        if exe is _FALLBACK:
            return self.jit_fn()(*args, **statics)
        try:
            # static args are baked into the compiled executable: call
            # positionally with the traced args only
            return exe(*args)
        except Exception as exc:
            log.debug("AOT executable %s rejected args (%s); falling back "
                      "to jit", self.name, exc)
            mgr._remember(key, _FALLBACK)
            mgr.count("exec_fallbacks")
            return self.jit_fn()(*args, **statics)


class JitEntry:
    """Registered plain-jit entry: no AOT dispatch, but recompiles are
    detected (via the PjitFunction cache size) and counted, so the
    zero-recompile acceptance check sees every entry in the stack."""

    def __init__(self, manager: "CompileManager", name: str,
                 jfn: Callable,
                 donate_argnums: Tuple[int, ...] = ()) -> None:
        self.manager = manager
        self.name = name
        self.donate_argnums = tuple(donate_argnums)
        self._jfn = jfn

    def __getattr__(self, item: str) -> Any:
        return getattr(self._jfn, item)

    def _cache_size(self) -> Optional[int]:
        try:
            return self._jfn._cache_size()
        except Exception:
            return None

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if self.donate_argnums:
            _count_donated_bytes(self.donate_argnums, args)
        before = self._cache_size()
        t0 = time.perf_counter()
        out = self._jfn(*args, **kwargs)
        if before is not None:
            after = self._cache_size()
            if after is not None and after > before:
                # first call traces+compiles+runs; attributing the whole
                # call to compile slightly overcounts by one execution
                self.manager.count("jit_compiles")
                # each cache growth is one more distinct traced program
                self.manager.count("programs", after - before)
                self.manager.add_time("compile", time.perf_counter() - t0)
        return out


class CompileManager:
    def __init__(self) -> None:
        self.store = ExecutableStore()
        self.shared: "collections.OrderedDict[str, SharedEntry]" = \
            collections.OrderedDict()
        self.executables: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self.stats: Dict[str, float] = {}
        self._lock = threading.Lock()
        # RLock: _compile holds it across .lower(), whose trace re-enters
        # it through fused.py _bind_tables on the same thread
        self._trace_lock = threading.RLock()
        self._key_locks: Dict[str, threading.Lock] = {}
        self.aot_enabled = store_enabled() and _aot_supported()

    # -- bookkeeping ----------------------------------------------------
    def count(self, name: str, value: float = 1) -> None:
        with self._lock:
            self.stats[name] = self.stats.get(name, 0) + value
        from .. import obs
        reg = obs.active()
        if reg is not None:
            reg.inc(f"compile.{name}", value)

    def add_time(self, phase: str, seconds: float) -> None:
        with self._lock:
            key = f"{phase}_s"
            self.stats[key] = self.stats.get(key, 0.0) + seconds
        from .. import obs
        reg = obs.active()
        if reg is not None:
            reg.add_time(phase, seconds)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.stats)

    # -- registration ---------------------------------------------------
    def shared_entry(self, name: str, sig: Any,
                     build: Callable[[], Callable],
                     donate_argnums: Tuple[int, ...] = (),
                     store: bool = True) -> SharedEntry:
        """The entry for (name, signature), creating it on first use.
        A pre-existing entry keeps ITS builder: signatures are defined
        precisely so equal digests trace identical programs.
        `donate_argnums` declares which positional args the built
        program donates; it refines the digest (and hence every AOT key
        under it), so toggling donation can never replay an executable
        with the wrong aliasing — and can never retrace one that has
        the right aliasing."""
        digest = S.signature_digest(name, sig, donate_argnums)
        with self._lock:
            entry = self.shared.get(digest)
            if entry is not None:
                self.shared.move_to_end(digest)
                return entry
            entry = SharedEntry(self, name, digest, build, donate_argnums,
                                store=store)
            self.shared[digest] = entry
            while len(self.shared) > _MAX_SHARED_ENTRIES:
                self.shared.popitem(last=False)
            return entry

    def jit_entry(self, name: str, jfn: Callable,
                  donate_argnums: Tuple[int, ...] = ()) -> JitEntry:
        return JitEntry(self, name, jfn, donate_argnums)

    # -- dispatch -------------------------------------------------------
    def _key_lock(self, key: str) -> threading.Lock:
        with self._lock:
            lk = self._key_locks.get(key)
            if lk is None:
                lk = self._key_locks[key] = threading.Lock()
            return lk

    def _remember(self, key: str, exe: Any) -> None:
        with self._lock:
            self.executables[key] = exe
            self.executables.move_to_end(key)
            while len(self.executables) > _MAX_EXECUTABLES:
                self.executables.popitem(last=False)

    def acquire(self, entry: SharedEntry, key: str, args: Any,
                statics: Dict[str, Any]) -> Any:
        """Executable for one concrete call: store load, else compile
        (+persist), else the fallback marker. `args` may be avals."""
        with self._key_lock(key):
            exe = self.executables.get(key)
            if exe is not None:
                self.count("cache_hits")
                return exe
            exe = self._load_from_store(entry, key)
            if exe is None:
                exe = self._compile(entry, key, args, statics)
            self._remember(key, exe)
            return exe

    def _load_from_store(self, entry: SharedEntry, key: str) -> Any:
        if not entry.store:
            return None
        try:
            t0 = time.perf_counter()
            triple = self.store.load(key)
            if triple is None:
                return None
            from jax.experimental.serialize_executable import \
                deserialize_and_load
            exe = deserialize_and_load(*triple)
            self.add_time("aot_load", time.perf_counter() - t0)
            self.count("store_loads")
            return exe
        except CorruptBlobError:
            self.count("store_load_errors")
            return None
        except Exception as exc:
            log.debug("AOT deserialize failed for %s (%s)", entry.name, exc)
            self.count("store_load_errors")
            self.store.invalidate(key)
            return None

    def _compile(self, entry: SharedEntry, key: str, args: Any,
                 statics: Dict[str, Any]) -> Any:
        try:
            from jax.experimental.serialize_executable import serialize
            t0 = time.perf_counter()
            with self._trace_lock:
                lowered = entry.jit_fn().lower(*args, **statics)
            t1 = time.perf_counter()
            exe = lowered.compile()
            elapsed = time.perf_counter() - t0
            self.add_time("compile", elapsed)
            # distinct-program accounting (obs schema v1.9): every real
            # compile is one program; `lowering_s` isolates the
            # trace+lower span (where the old per-width kernel unroll
            # burned its 70 minutes) from XLA compile proper
            self.count("programs")
            self.count("lowering_s", t1 - t0)
            self.count("cache_misses")
            # persist (and pay the HLO-text stat) only for compiles
            # slower than the threshold: sub-threshold programs cost
            # more in serialize + blob + manifest traffic than their
            # recompile, and `hlo_bytes` sizes what the store holds —
            # the programs the compile window is actually made of
            if entry.store and elapsed >= min_compile_s():
                try:
                    self.count("hlo_bytes", len(lowered.as_text()))
                except Exception:
                    pass
                t0 = time.perf_counter()
                triple = serialize(exe)
                if self.store.save(key, triple):
                    self.add_time("aot_serialize", time.perf_counter() - t0)
                    self.count("store_saves")
            return exe
        except Exception as exc:
            log.debug("AOT compile failed for %s (%s); using plain jit",
                      entry.name, exc)
            self.count("fallbacks")
            return _FALLBACK

    # -- store preload --------------------------------------------------
    def preload_keys(self) -> List[str]:
        """Store keys for the current environment not yet in memory."""
        if not self.aot_enabled:
            return []
        with self._lock:
            loaded = set(self.executables)
        return [k for k in self.store.keys() if k not in loaded]

    def preload(self, keys: Optional[List[str]] = None,
                should_stop: Optional[Callable[[], bool]] = None) -> int:
        """Deserialize stored executables into memory so the first
        training call is a pure cache hit. Returns how many loaded."""
        n = 0
        for key in (self.preload_keys() if keys is None else keys):
            if should_stop is not None and should_stop():
                break
            with self._key_lock(key):
                if key in self.executables:
                    continue
                exe = self._preload_one(key)
                if exe is not None:
                    self._remember(key, exe)
                    n += 1
        return n

    def _preload_one(self, key: str) -> Any:
        try:
            t0 = time.perf_counter()
            triple = self.store.load(key)
            if triple is None:
                return None
            from jax.experimental.serialize_executable import \
                deserialize_and_load
            exe = deserialize_and_load(*triple)
            self.add_time("aot_load", time.perf_counter() - t0)
            self.count("store_preloads")
            return exe
        except Exception:
            self.count("store_load_errors")
            self.store.invalidate(key)
            return None


_MANAGER: Optional[CompileManager] = None
_MANAGER_LOCK = threading.Lock()


def get_manager() -> CompileManager:
    global _MANAGER
    if _MANAGER is None:
        with _MANAGER_LOCK:
            if _MANAGER is None:
                _MANAGER = CompileManager()
    return _MANAGER


def reset_manager() -> None:
    """Drop the process-global manager (tests)."""
    global _MANAGER
    with _MANAGER_LOCK:
        _MANAGER = None


@atexit.register
def _drop_executables() -> None:
    """Destroy loaded executables while the runtime is still healthy.

    XLA:CPU aborts the process ("terminate called without an active
    exception") when an executable produced by deserialize_and_load is
    still referenced during interpreter teardown; releasing them from
    Python-side atexit sequences their destructors before the client's.
    """
    mgr = _MANAGER
    if mgr is not None:
        with mgr._lock:
            mgr.executables.clear()
