"""On-disk store of serialized XLA executables.

Layout: <root>/<environment_key>/<cache_key>.aotx — one pickled payload
per executable holding the `jax.export`-level serialization triple
(blob, in_tree, out_tree) produced by
`jax.experimental.serialize_executable.serialize`. The environment-key
directory namespaces by (jax version, backend, device kind/count,
process count), so upgrading jax or moving between CPU/TPU can never
deserialize a stale executable — it simply looks in a different
directory. Within a directory, keys already encode the compile
signature and bucketed shapes (signature.py), so files are immutable:
invalidation is deletion, never rewrite.

Root: $LGBM_TPU_AOT_CACHE, default ~/.cache/lightgbm_tpu/aot.
LGBM_TPU_AOT=0 disables the store (and all AOT dispatch) entirely.

Corrupt or undeserializable blobs are deleted and reported through the
manager's counters; callers fall back to plain jit.

TRUST BOUNDARY: the cache directory must only be writable by the user
running training. Payloads are pickled (the serialized triple's
in/out pytrees have no stable non-pickle encoding, and jax's own
deserialize_and_load unpickles the blob regardless), so a tampered
.aotx file executes arbitrary code at load time — exactly like jax's
persistent compilation cache. The store therefore creates its
directories 0700 and blob files 0600. Do not point $LGBM_TPU_AOT_CACHE
at a world- or group-writable path; the default is per-user, and its
contents deserve the same trust as ~/.cache/jax.
"""
from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, List, Optional, Tuple

import jax

from ..utils import log
from . import signature as S

_PAYLOAD_VERSION = 1


def store_enabled() -> bool:
    return os.environ.get("LGBM_TPU_AOT", "1") != "0"


def default_root() -> str:
    return os.environ.get(
        "LGBM_TPU_AOT_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "lightgbm_tpu",
                     "aot"))


class ExecutableStore:
    """Filesystem store; all methods are best-effort and exception-free
    (a broken disk must never break training)."""

    def __init__(self, root: Optional[str] = None) -> None:
        self.root = root or default_root()
        self._env_dir: Optional[str] = None

    def env_dir(self) -> str:
        if self._env_dir is None:
            self._env_dir = os.path.join(self.root, S.environment_key())
        return self._env_dir

    def path(self, key: str) -> str:
        return os.path.join(self.env_dir(), key + ".aotx")

    def keys(self) -> List[str]:
        try:
            return sorted(f[:-5] for f in os.listdir(self.env_dir())
                          if f.endswith(".aotx"))
        except OSError:
            return []

    def load(self, key: str) -> Optional[Tuple[bytes, Any, Any]]:
        """The serialized triple for `key`, or None. Corrupt payloads
        (unpicklable, wrong version, truncated) are deleted on sight."""
        path = self.path(key)
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
            from ..robust.faultinject import filter_bytes
            raw = filter_bytes("store.load", raw)
            payload = pickle.loads(raw)
            if (not isinstance(payload, dict)
                    or payload.get("v") != _PAYLOAD_VERSION
                    or payload.get("jax") != jax.__version__):
                raise ValueError("payload version mismatch")
            return payload["blob"], payload["in_tree"], payload["out_tree"]
        except FileNotFoundError:
            return None
        except (EOFError, pickle.UnpicklingError) as exc:
            # a crash mid-save (or a torn copy) leaves a short pickle:
            # same recovery as any other corruption, but named so the
            # fallback is visibly about truncation, not version drift
            log.debug("AOT store: dropping truncated/corrupt pickle %s (%s)",
                      path, exc)
            self.invalidate(key)
            raise CorruptBlobError(
                f"truncated or corrupt pickle: {exc}") from exc
        except Exception as exc:
            log.debug("AOT store: dropping corrupt blob %s (%s)", path, exc)
            self.invalidate(key)
            raise CorruptBlobError(str(exc)) from exc

    def _ensure_dirs(self) -> None:
        """Create root + env dir owner-only (0700): blobs are pickled,
        so the directory is a code-execution surface for anyone who can
        write to it (module docstring, TRUST BOUNDARY)."""
        if os.path.isdir(self.env_dir()):
            return
        created = [d for d in (self.root, self.env_dir())
                   if not os.path.isdir(d)]
        os.makedirs(self.env_dir(), mode=0o700, exist_ok=True)
        for d in created:
            try:
                os.chmod(d, 0o700)  # makedirs mode is masked by umask
            except OSError:
                pass

    def save(self, key: str, triple: Tuple[bytes, Any, Any]) -> bool:
        """Atomically persist a serialized triple (tmp file + rename, so
        a concurrent reader never sees a torn write)."""
        try:
            self._ensure_dirs()
            payload = {"v": _PAYLOAD_VERSION, "jax": jax.__version__,
                       "key": key, "blob": triple[0],
                       "in_tree": triple[1], "out_tree": triple[2]}
            fd, tmp = tempfile.mkstemp(dir=self.env_dir(), suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    pickle.dump(payload, fh, protocol=pickle.HIGHEST_PROTOCOL)
                os.replace(tmp, self.path(key))
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            return True
        except Exception as exc:
            log.debug("AOT store: save failed for %s (%s)", key, exc)
            return False

    def invalidate(self, key: str) -> None:
        try:
            os.unlink(self.path(key))
        except OSError:
            pass


class CorruptBlobError(RuntimeError):
    """A stored payload existed but could not be decoded."""
