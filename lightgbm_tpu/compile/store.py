"""On-disk store of serialized XLA executables — pod-shared and
content-addressed.

Layout under <root>/<environment_key>/ (one flat directory per
environment, rsync/GCS-friendly):

- ``sha256-<digest>.aotx`` — immutable content-addressed blobs. The
  digest is over the pickled payload bytes, so a blob's name fully
  determines its contents: concurrent writers racing on the same
  payload write the same file, a torn copy can never be confused with
  a good one, and `rsync --ignore-existing` / `gsutil -m cp -n` are
  safe fleet-distribution primitives.
- ``manifest.json`` — maps cache keys to blob names (plus nbytes and a
  created stamp). Rewritten atomically (tmp + rename) with a
  read-merge-write, so publishers racing on different keys lose at
  most each other's single entry — and a key whose manifest entry is
  lost falls back to recompile, never to a wrong executable.
- ``<cache_key>.aotx`` — legacy direct-keyed blobs from earlier
  versions, still probed on load so pre-manifest stores keep working.

Each payload holds the `jax.export`-level serialization triple
(blob, in_tree, out_tree) produced by
`jax.experimental.serialize_executable.serialize`. The environment-key
directory namespaces by (jax version, backend, device kind/count,
process count, code fingerprint), so upgrading jax or moving between
CPU/TPU can never deserialize a stale executable — it simply looks in
a different directory.

Publish protocol (pod-shared writers): blob first (tmp + rename; skip
the write when the digest already exists), manifest second. A reader
that sees the manifest entry therefore always sees the complete blob.

GC: a size-capped mtime-LRU sweep runs after each save. Blob mtimes
are touched on load, so the LRU order reflects use, not creation.
Knobs: LGBM_TPU_AOT_CACHE_MB caps the per-environment directory size
(default 2048; 0 disables the sweep).

Root: $LGBM_TPU_AOT_CACHE, default ~/.cache/lightgbm_tpu/aot.
LGBM_TPU_AOT=0 disables the store (and all AOT dispatch) entirely.

Corrupt or undeserializable blobs are deleted and reported through the
manager's counters; a corrupt manifest is treated as empty (recompile,
then rewritten on the next save); callers fall back to plain jit.

TRUST BOUNDARY: the cache directory must only be writable by the user
(or pod service account) running training. Payloads are pickled (the
serialized triple's in/out pytrees have no stable non-pickle encoding,
and jax's own deserialize_and_load unpickles the blob regardless), so
a tampered .aotx file executes arbitrary code at load time — exactly
like jax's persistent compilation cache. The store therefore creates
its directories 0700 and files 0600. Content addressing is an
*integrity* check against corruption, not an authenticity check: the
manifest and digests live in the same directory as the blobs, so
anyone who can write a blob can write its digest. Do not point
$LGBM_TPU_AOT_CACHE at a world- or group-writable path, and only
rsync/mount stores from pods you trust as much as the training user;
the default is per-user, and its contents deserve the same trust as
~/.cache/jax.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import jax

from ..utils import log
from . import signature as S

_PAYLOAD_VERSION = 1
_MANIFEST_VERSION = 1
_MANIFEST_NAME = "manifest.json"
_BLOB_PREFIX = "sha256-"


def store_enabled() -> bool:
    return os.environ.get("LGBM_TPU_AOT", "1") != "0"


def default_root() -> str:
    return os.environ.get(
        "LGBM_TPU_AOT_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "lightgbm_tpu",
                     "aot"))


def cache_cap_bytes() -> int:
    """Per-environment directory size cap for the mtime-LRU sweep.
    0 disables GC."""
    try:
        mb = int(os.environ.get("LGBM_TPU_AOT_CACHE_MB", 2048))
    except ValueError:
        mb = 2048
    return max(mb, 0) * (1 << 20)


def min_compile_s() -> float:
    """Persistence threshold: compiles faster than this are not worth a
    serialize + blob + manifest round-trip (the recompile is cheaper
    than the disk traffic, and tiny programs would dominate the blob
    count without moving the compile window). Mirrors jax's
    `jax_persistent_cache_min_compile_time_secs`. 0 persists everything
    (the fixture setting for store tests)."""
    try:
        return float(os.environ.get("LGBM_TPU_AOT_MIN_COMPILE_S", 0.5))
    except ValueError:
        return 0.5


class ExecutableStore:
    """Filesystem store; all methods are best-effort and exception-free
    (a broken disk must never break training)."""

    def __init__(self, root: Optional[str] = None) -> None:
        self.root = root or default_root()
        self._env_dir: Optional[str] = None

    def env_dir(self) -> str:
        if self._env_dir is None:
            # tpulint: thread-ok(idempotent lazy cache; racing threads compute equal paths)
            self._env_dir = os.path.join(self.root, S.environment_key())
        return self._env_dir

    def path(self, key: str) -> str:
        """Legacy direct-keyed blob location (pre-manifest stores)."""
        return os.path.join(self.env_dir(), key + ".aotx")

    def manifest_path(self) -> str:
        return os.path.join(self.env_dir(), _MANIFEST_NAME)

    # -- manifest -------------------------------------------------------
    def _read_manifest(self) -> Dict[str, Any]:
        """Key → {blob, nbytes, created}. A corrupt or missing manifest
        is an EMPTY one: readers fall back to recompile and the next
        save rewrites it — never a crash."""
        try:
            with open(self.manifest_path(), "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            if (not isinstance(doc, dict)
                    or doc.get("v") != _MANIFEST_VERSION
                    or not isinstance(doc.get("entries"), dict)):
                raise ValueError("manifest shape mismatch")
            return doc["entries"]
        except FileNotFoundError:
            return {}
        except Exception as exc:
            log.debug("AOT store: unreadable manifest %s (%s); treating "
                      "as empty", self.manifest_path(), exc)
            return {}

    def _write_manifest(self, entries: Dict[str, Any]) -> None:
        doc = {"v": _MANIFEST_VERSION, "env": S.environment_key(),
               "entries": entries}
        fd, tmp = tempfile.mkstemp(dir=self.env_dir(), suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, sort_keys=True, separators=(",", ":"))
            os.chmod(tmp, 0o600)
            os.replace(tmp, self.manifest_path())
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def _update_manifest(self, key: str, entry: Optional[Dict[str, Any]]
                         ) -> None:
        """Read-merge-write one manifest entry (None deletes)."""
        entries = self._read_manifest()
        if entry is None:
            if key not in entries:
                return
            del entries[key]
        else:
            entries[key] = entry
        self._write_manifest(entries)

    # -- enumeration ----------------------------------------------------
    def keys(self) -> List[str]:
        """Manifest keys plus legacy direct-keyed blob stems."""
        out = set(self._read_manifest())
        try:
            for f in os.listdir(self.env_dir()):
                if f.endswith(".aotx") and not f.startswith(_BLOB_PREFIX):
                    out.add(f[:-5])
        except OSError:
            pass
        return sorted(out)

    # -- load -----------------------------------------------------------
    def load(self, key: str) -> Optional[Tuple[bytes, Any, Any]]:
        """The serialized triple for `key`, or None. Manifest entries
        are probed first, then the legacy direct path. Corrupt payloads
        (unpicklable, wrong version, truncated) are deleted on sight;
        a manifest entry pointing at a missing/corrupt blob is dropped
        and reported as corruption (caller recompiles)."""
        entry = self._read_manifest().get(key)
        via_manifest = isinstance(entry, dict) and \
            isinstance(entry.get("blob"), str)
        if via_manifest:
            path = os.path.join(self.env_dir(), entry["blob"])
        else:
            if entry is not None:
                # entry exists but is malformed — same recovery as a
                # corrupt blob: drop it and recompile
                self._best_effort(self._update_manifest, key, None)
                raise CorruptBlobError("malformed manifest entry")
            path = self.path(key)
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
            from ..robust.faultinject import filter_bytes
            raw = filter_bytes("store.load", raw)
            if via_manifest:
                digest = os.path.basename(path)[len(_BLOB_PREFIX):-5]
                if hashlib.sha256(raw).hexdigest()[:32] != digest:
                    raise ValueError(
                        "truncated or corrupt blob: content digest mismatch")
            payload = pickle.loads(raw)
            if (not isinstance(payload, dict)
                    or payload.get("v") != _PAYLOAD_VERSION
                    or payload.get("jax") != jax.__version__):
                raise ValueError("payload version mismatch")
            # LRU touch: GC evicts by mtime, so a loaded blob is "young"
            self._best_effort(os.utime, path)
            return payload["blob"], payload["in_tree"], payload["out_tree"]
        except FileNotFoundError:
            if via_manifest:
                # manifest promised a blob that is gone (GC race on
                # another pod, partial rsync): recompile, not a crash
                self._best_effort(self._update_manifest, key, None)
                raise CorruptBlobError("manifest entry without blob")
            return None
        except (EOFError, pickle.UnpicklingError) as exc:
            # a crash mid-save (or a torn copy) leaves a short pickle:
            # same recovery as any other corruption, but named so the
            # fallback is visibly about truncation, not version drift
            log.debug("AOT store: dropping truncated/corrupt pickle %s (%s)",
                      path, exc)
            self.invalidate(key)
            raise CorruptBlobError(
                f"truncated or corrupt pickle: {exc}") from exc
        except CorruptBlobError:
            raise
        except Exception as exc:
            log.debug("AOT store: dropping corrupt blob %s (%s)", path, exc)
            self.invalidate(key)
            raise CorruptBlobError(str(exc)) from exc

    # -- save -----------------------------------------------------------
    def _ensure_dirs(self) -> None:
        """Create root + env dir owner-only (0700): blobs are pickled,
        so the directory is a code-execution surface for anyone who can
        write to it (module docstring, TRUST BOUNDARY)."""
        if os.path.isdir(self.env_dir()):
            return
        created = [d for d in (self.root, self.env_dir())
                   if not os.path.isdir(d)]
        os.makedirs(self.env_dir(), mode=0o700, exist_ok=True)
        for d in created:
            try:
                os.chmod(d, 0o700)  # makedirs mode is masked by umask
            except OSError:
                pass

    def save(self, key: str, triple: Tuple[bytes, Any, Any]) -> bool:
        """Content-addressed atomic publish: blob first (tmp + rename,
        skipped when the digest already exists), manifest entry second.
        A concurrent reader that sees the entry sees the whole blob."""
        try:
            self._ensure_dirs()
            # no key field in the payload: the blob name is a pure
            # content digest, so two keys whose compiles produced the
            # same serialized triple share one blob on disk
            payload = {"v": _PAYLOAD_VERSION, "jax": jax.__version__,
                       "blob": triple[0],
                       "in_tree": triple[1], "out_tree": triple[2]}
            raw = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
            blob_name = (_BLOB_PREFIX
                         + hashlib.sha256(raw).hexdigest()[:32] + ".aotx")
            blob_path = os.path.join(self.env_dir(), blob_name)
            if not os.path.exists(blob_path):
                fd, tmp = tempfile.mkstemp(dir=self.env_dir(),
                                           suffix=".tmp")
                try:
                    with os.fdopen(fd, "wb") as fh:
                        fh.write(raw)
                    os.chmod(tmp, 0o600)
                    os.replace(tmp, blob_path)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            self._update_manifest(key, {"blob": blob_name,
                                        "nbytes": len(raw),
                                        "created": time.time()})
            self._best_effort(self.gc)
            return True
        except Exception as exc:
            log.debug("AOT store: save failed for %s (%s)", key, exc)
            return False

    # -- invalidate / GC ------------------------------------------------
    def invalidate(self, key: str) -> None:
        """Drop a key: its manifest entry, its blob (content-addressed
        blobs are only ever referenced through manifest entries whose
        keys encode the same payload, so a corrupt blob is corrupt for
        every key that names it), and any legacy direct file."""
        entries = self._read_manifest()
        entry = entries.get(key)
        if isinstance(entry, dict) and isinstance(entry.get("blob"), str):
            self._best_effort(
                os.unlink, os.path.join(self.env_dir(), entry["blob"]))
        if key in entries:
            del entries[key]
            self._best_effort(self._write_manifest, entries)
        try:
            os.unlink(self.path(key))
        except OSError:
            pass

    def gc(self, cap_bytes: Optional[int] = None) -> int:
        """Size-capped mtime-LRU sweep over the environment directory.
        Deletes oldest-used blobs until the directory fits the cap,
        then drops the manifest entries that pointed at them. Returns
        how many blobs were deleted. Best-effort: every step tolerates
        concurrent writers and sweepers."""
        cap = cache_cap_bytes() if cap_bytes is None else cap_bytes
        if cap <= 0:
            return 0
        try:
            blobs = []
            total = 0
            for f in os.listdir(self.env_dir()):
                if not f.endswith(".aotx"):
                    continue
                p = os.path.join(self.env_dir(), f)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                blobs.append((st.st_mtime, st.st_size, f, p))
                total += st.st_size
            if total <= cap:
                return 0
            blobs.sort()  # oldest mtime first
            deleted = set()
            for mtime, size, name, p in blobs:
                if total <= cap:
                    break
                try:
                    os.unlink(p)
                except OSError:
                    continue
                total -= size
                deleted.add(name)
            if deleted:
                entries = self._read_manifest()
                kept = {k: e for k, e in entries.items()
                        if not (isinstance(e, dict)
                                and e.get("blob") in deleted)}
                if len(kept) != len(entries):
                    self._best_effort(self._write_manifest, kept)
                log.debug("AOT store: GC evicted %d blob(s) to fit "
                          "%d MB", len(deleted), cap >> 20)
            return len(deleted)
        except OSError:
            return 0

    @staticmethod
    def _best_effort(fn, *args) -> None:
        try:
            fn(*args)
        except Exception:
            pass


class CorruptBlobError(RuntimeError):
    """A stored payload existed but could not be decoded."""
