"""Ahead-of-time warmup: compile (or preload) executables off the
critical path.

Three entry points:

- `preload_store_async()` — fired by `engine.train()` before the
  Dataset/Booster build: a daemon thread deserializes every stored
  executable for the current environment into the manager's memory
  cache, overlapping with binning/quantization host work.
- `background_warmup(booster)` — fired after the Booster is built: a
  thread pool compiles every registered-but-uncompiled warmup spec
  concurrently with the first training iterations. Gated (rows >=
  LGBM_TPU_BUCKET_MIN or tpu_warmup=true / LGBM_TPU_WARMUP=1) so small
  jobs and tests don't spawn threads for sub-second compiles.
- `run_warmup(params)` — the `python -m lightgbm_tpu warmup` CLI: build
  the Dataset + Booster exactly as training would (registering every
  entry), compile all specs to completion, persist them, and report.
  A later `train()`/`bench.py` process with the same signature then
  deserializes instead of compiling.
"""
from __future__ import annotations

import atexit
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ..utils import log
from . import signature as S
from .manager import (CompileManager, SharedEntry, get_manager,
                      is_executable)

# Background threads must never be mid-XLA-call while the interpreter
# tears down its C++ state (PJRT client destruction aborts the process
# with "terminate called without an active exception"). Every thread
# checks `_shutdown` between work items, and the atexit hook set here
# joins them before teardown.
_shutdown = threading.Event()
_bg_threads: List[threading.Thread] = []
_bg_lock = threading.Lock()


def _track(th: threading.Thread) -> threading.Thread:
    with _bg_lock:
        _bg_threads.append(th)
        live = [t for t in _bg_threads if t.is_alive()]
        _bg_threads[:] = live
    return th


@atexit.register
def _join_background_threads() -> None:
    _shutdown.set()
    with _bg_lock:
        threads = list(_bg_threads)
    for th in threads:
        th.join()


def _pending_specs(mgr: CompileManager
                   ) -> List[Tuple[SharedEntry, str, Any, Dict[str, Any]]]:
    out = []
    seen = set()
    for entry in list(mgr.shared.values()):
        # snapshot under the entry lock: learners may still be
        # registering specs while a warmup thread walks the list
        with entry._lock:
            specs = list(entry.specs)
        for args, statics in specs:
            key = entry.key_for(args, statics)
            # dedupe across entries too: signature bucketing can
            # collide specs from different learners (serial/fused/MC
            # variants) onto one key — compile each shared signature
            # exactly once
            if key in seen:
                continue
            seen.add(key)
            if mgr.executables.get(key) is None:
                out.append((entry, key, args, statics))
    return out


def warmup_entries(jobs: Optional[int] = None) -> Dict[str, Any]:
    """Compile every registered warmup spec not already executable;
    blocks until done. Returns a summary dict."""
    mgr = get_manager()
    if not mgr.aot_enabled:
        return {"entries": 0, "compiled": 0, "seconds": 0.0,
                "disabled": True}
    pending = _pending_specs(mgr)
    t0 = time.perf_counter()
    compiled = 0
    if pending:
        workers = max(1, jobs or min(4, len(pending)))

        def _one(item):
            if _shutdown.is_set():
                return None
            entry, key, args, statics = item
            return mgr.acquire(entry, key, args, statics)

        with ThreadPoolExecutor(max_workers=workers) as pool:
            for exe in pool.map(_one, pending):
                # a _FALLBACK result means the compile FAILED — only
                # real executables count toward the warmup summary
                compiled += is_executable(exe)
    return {"entries": len(pending), "compiled": compiled,
            "seconds": time.perf_counter() - t0,
            "stats": mgr.snapshot()}


def preload_store_async() -> Optional[threading.Thread]:
    """Deserialize stored executables on a daemon thread; returns the
    thread (None when there is nothing to do)."""
    if os.environ.get("LGBM_TPU_AOT_PRELOAD", "1") == "0":
        return None
    mgr = get_manager()
    if not mgr.aot_enabled or not mgr.preload_keys():
        return None
    th = threading.Thread(
        target=lambda: mgr.preload(should_stop=_shutdown.is_set),
        name="lgbm-aot-preload", daemon=True)
    _track(th)
    th.start()
    return th


def warmup_wanted(config: Any, num_data: int) -> bool:
    env = os.environ.get("LGBM_TPU_WARMUP", "")
    if env in ("0", "false"):
        return False
    if env in ("1", "true") or getattr(config, "tpu_warmup", False):
        return True
    return num_data >= S.bucket_min_rows()


def background_warmup(jobs: Optional[int] = None
                      ) -> Optional[threading.Thread]:
    """Compile pending warmup specs on daemon threads, concurrent with
    the first training iterations."""
    mgr = get_manager()
    if not mgr.aot_enabled:
        return None

    def _run() -> None:
        try:
            summary = warmup_entries(jobs=jobs)
            if summary["entries"]:
                log.debug("Background warmup compiled %d/%d entries in "
                          "%.1fs", summary["compiled"], summary["entries"],
                          summary["seconds"])
        except Exception as exc:
            log.debug("Background warmup failed: %s", exc)

    th = threading.Thread(target=_run, name="lgbm-aot-warmup", daemon=True)
    _track(th)
    th.start()
    return th


def run_warmup(config: Any, params: Dict[str, str]) -> Dict[str, Any]:
    """CLI warmup task: construct the Dataset + Booster exactly as
    `task=train` would (which registers every jit entry point and its
    warmup specs), then compile + persist all of them."""
    import lightgbm_tpu as lgb

    if not config.data:
        raise ValueError("task=warmup requires data= (the dataset file "
                         "whose shapes/params define the executables)")
    clean = {k: v for k, v in params.items() if k not in ("task",)}
    train_set = lgb.Dataset(config.data, params=dict(clean))
    booster = lgb.Booster(params=dict(clean), train_set=train_set)
    summary = warmup_entries()
    mgr = get_manager()
    summary["store_dir"] = mgr.store.env_dir()
    summary["num_data"] = train_set.num_data()
    del booster
    return summary
