"""Compile signatures, cache keys, and canonical shape bucketing.

A *compile signature* reduces (params, dataset statics, topology) to the
minimal set of values that change the traced program, so one serialized
executable can serve many datasets. The pieces:

- `bucket_rows(n)`: canonical row buckets. Datasets whose row counts land
  in the same bucket share every row-shaped executable; the pad rows are
  masked out by a traced row-count argument inside the kernels.
- `environment_key()`: (jax version, backend, device kind/count,
  process count, x64 mode, package code fingerprint) — anything that
  invalidates a serialized XLA executable wholesale. The code
  fingerprint digests the package's own .py sources, so editing any
  traced program (a kernel, a learner, an objective) moves the store to
  a fresh directory instead of silently replaying a stale executable —
  the same reason jax's compilation cache folds in its own version.
- `signature_digest(name, sig)`: entry-point identity. Two jit entries
  with equal digests trace byte-identical programs and may share one
  compiled executable (all dataset-varying arrays are traced arguments).
- `cache_key(base, shape_sig)`: final per-executable key = entry digest
  refined by the concrete argument avals.

Env knobs: LGBM_TPU_SHAPE_BUCKETS=0 disables bucketing;
LGBM_TPU_BUCKET_MIN overrides the row count below which datasets keep
their exact shape (default 1<<20 — small jobs compile fast anyway and
padding them wastes proportionally more memory).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Dict, Tuple

import jax

# Quarter-power-of-two bucket ladder: successive buckets differ by at
# most 25%, so padding waste is bounded by 25% of rows while the number
# of distinct buckets between 1M and 1B rows stays at ~40.
_BUCKET_SUBSTEPS = 4

_IGNORED_CONFIG_FIELDS = frozenset({
    # pure I/O, logging, and observability — never traced
    "data", "valid", "input_model", "output_model", "output_result",
    "convert_model", "convert_model_language", "initscore_filename",
    "valid_data_initscores", "forcedsplits_filename", "forcedbins_filename",
    "save_binary", "snapshot_freq", "header", "label_column",
    "weight_column", "group_column", "ignore_column", "categorical_feature",
    "two_round", "machines", "machine_list_filename", "time_out",
    "verbosity", "metrics_file", "profile_dir", "metrics_interval",
    "trace_file", "trace_buffer_events",
    "timetag", "tpu_warmup", "extra", "task", "data_random_seed",
    "metric_freq", "is_provide_training_metric",
    "eval_at", "num_machines", "local_listen_port",
    # fault tolerance: where/how often checkpoints land never changes
    # any traced program — resuming with a different checkpoint_dir
    # must hit the same executables
    "checkpoint_dir", "checkpoint_interval", "checkpoint_keep",
    # self-healing: the watchdog is host-side, and the sentinel takes
    # its overflow limit as a runtime scalar operand — toggling either
    # must hit the same executables (zero new compiles on a warm store)
    "hang_timeout", "auto_resume", "auto_resume_attempts",
    "numeric_sentinels", "sentinel_overflow_limit", "sentinel_max_trips",
    # pod-scale observability plane: the endpoint, fleet aggregation
    # and flight recorder are host-side — turning them on must warm
    # zero new compiles
    "obs_port", "flight_dir", "flight_slo_factor", "fleet_metrics",
})


def bucketing_enabled() -> bool:
    return os.environ.get("LGBM_TPU_SHAPE_BUCKETS", "1") != "0"


def bucket_min_rows() -> int:
    try:
        return int(os.environ.get("LGBM_TPU_BUCKET_MIN", 1 << 20))
    except ValueError:
        return 1 << 20


def bucket_rows(n: int) -> int:
    """Smallest canonical bucket >= n, or n itself below the threshold.

    Buckets are (2**k) * (1 + j/4) for j in 0..3 — each at most 25%
    above the previous, so the padded-row overhead a dataset pays for
    executable reuse is bounded by 25%.
    """
    lo = bucket_min_rows()
    if not bucketing_enabled() or n <= lo:
        return n
    k = max(int(n - 1).bit_length() - 1, 0)
    base = 1 << k
    for j in range(_BUCKET_SUBSTEPS + 1):
        b = base + (base * j) // _BUCKET_SUBSTEPS
        if b >= n:
            return b
    return base * 2  # unreachable; bit_length guarantees n <= 2*base


def _jsonable(v: Any) -> Any:
    """Canonical JSON-friendly form of a signature component."""
    if isinstance(v, (str, int, bool)) or v is None:
        return v
    if isinstance(v, float):
        return repr(v)  # exact round-trip, no 0.1 drift
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(v[k]) for k in sorted(v, key=str)}
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {f.name: _jsonable(getattr(v, f.name))
                for f in dataclasses.fields(v)}
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        return ["aval", list(v.shape), str(v.dtype)]
    return repr(v)


def _digest(obj: Any) -> str:
    payload = json.dumps(_jsonable(obj), sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:20]


def config_signature(config: Any) -> Dict[str, Any]:
    """Trace-relevant view of a Config: every field except pure I/O and
    observability ones. Over-inclusion only splits the cache; UNDER-
    inclusion would alias distinct programs, so unknown fields stay in."""
    out = {}
    for f in dataclasses.fields(config):
        if f.name in _IGNORED_CONFIG_FIELDS:
            continue
        out[f.name] = _jsonable(getattr(config, f.name))
    return out


_CODE_FINGERPRINT: str = ""


def code_fingerprint() -> str:
    """Digest of the package's own .py sources (paths + contents).

    Serialized executables bake in the traced program, so any code
    change — not just config changes — must invalidate them. Hashing
    the sources rather than a version string means dev checkouts and
    patched installs invalidate correctly without a version bump."""
    global _CODE_FINGERPRINT
    if not _CODE_FINGERPRINT:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        files = []
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            files += [os.path.join(dirpath, f) for f in filenames
                      if f.endswith(".py")]
        h = hashlib.sha256()
        for path in sorted(files):
            h.update(os.path.relpath(path, pkg).encode())
            try:
                with open(path, "rb") as fh:
                    h.update(fh.read())
            except OSError:
                h.update(b"<unreadable>")
        _CODE_FINGERPRINT = h.hexdigest()[:20]
    return _CODE_FINGERPRINT


def environment_key() -> str:
    try:
        from .. import __version__ as pkg_version
    except Exception:
        pkg_version = "unknown"
    devs = jax.devices()
    env = {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": devs[0].device_kind if devs else "none",
        "device_count": len(devs),
        "process_count": jax.process_count(),
        # x64 changes every traced dtype, hence every executable
        "x64": bool(jax.config.jax_enable_x64),
        "package": pkg_version,
        "code": code_fingerprint(),
    }
    return _digest(env)


def signature_digest(name: str, sig: Any,
                     donate_argnums: Tuple[int, ...] = ()) -> str:
    """Entry-point identity. Donation is part of the traced program
    (XLA bakes input/output aliasing into the executable), so donating
    entries must never alias a non-donating executable of the same
    name+sig — the donate tuple joins the digest. Omitted when empty so
    every pre-existing non-donating digest (and its serialized store
    blobs) stays byte-identical."""
    payload: Dict[str, Any] = {"entry": name, "sig": sig}
    if donate_argnums:
        payload["donate"] = sorted(int(i) for i in donate_argnums)
    return _digest(payload)


def shape_signature(args: Any, statics: Dict[str, Any]) -> Tuple:
    """Hashable aval signature of one concrete call: (treedef, leaf
    shapes/dtypes, sorted statics). Works on arrays and ShapeDtypeStructs
    alike, so warmup specs and live calls produce the same key."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    leaf_sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            leaf_sig.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            leaf_sig.append(("py", repr(leaf)))
    return (str(treedef), tuple(leaf_sig),
            tuple(sorted((k, _jsonable(v)) for k, v in statics.items())))


def cache_key(base_digest: str, shape_sig: Tuple) -> str:
    h = hashlib.sha256(base_digest.encode())
    h.update(repr(shape_sig).encode())
    return h.hexdigest()[:32]
