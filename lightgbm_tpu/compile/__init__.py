"""AOT compile manager (docs/COMPILE_CACHE.md).

Makes compiled XLA executables first-class artifacts: a registry of the
stack's jit entry points, canonical shape bucketing so one executable
serves many datasets, a serialized executable store keyed by
(environment, compile signature, bucketed shapes), and parallel /
background warmup that takes compilation off the training critical
path.

Quick map:

- signature.py — buckets, signatures, cache keys
- store.py     — on-disk serialized executables
- manager.py   — registration + AOT-first dispatch + counters
- warmup.py    — preload / background / CLI warmup drivers
"""
from __future__ import annotations

from .manager import (CompileManager, JitEntry, SharedEntry, get_manager,
                      reset_manager)
from .signature import (bucket_rows, bucketing_enabled, bucket_min_rows,
                        cache_key, config_signature, environment_key,
                        shape_signature, signature_digest)
from .store import CorruptBlobError, ExecutableStore, store_enabled
from .warmup import (background_warmup, preload_store_async, run_warmup,
                     warmup_entries, warmup_wanted)

__all__ = [
    "CompileManager", "JitEntry", "SharedEntry", "get_manager",
    "reset_manager", "bucket_rows", "bucketing_enabled", "bucket_min_rows",
    "cache_key", "config_signature", "environment_key", "shape_signature",
    "signature_digest", "CorruptBlobError", "ExecutableStore",
    "store_enabled", "background_warmup", "preload_store_async",
    "run_warmup", "warmup_entries", "warmup_wanted",
]
