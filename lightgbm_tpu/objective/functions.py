"""Objective functions (gradient/hessian producers).

TPU re-design of the reference objective layer
(reference: src/objective/ — factory at objective_function.cpp:15-52;
regression_objective.hpp, binary_objective.hpp, multiclass_objective.hpp,
xentropy_objective.hpp, rank_objective.hpp). Per-row OpenMP loops become
jitted jnp element-wise programs over the score array; the ranking
objectives build padded per-query segments instead of per-query scalar
loops (no sigmoid lookup table — transcendentals are cheap on the VPU).

Every objective exposes:
- ``get_gradients(score) -> (grad, hess)``  [device, jitted]
- ``boost_from_score(class_id) -> float``   (BoostFromScore)
- ``convert_output(raw)``                   (ConvertOutput)
- ``is_renew_tree_output`` / ``renew_tree_output(...)`` leaf refits
  (L1/quantile/MAPE percentile refits, RenewTreeOutput)
- ``num_tree_per_iteration`` (num_class for softmax)
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config
from ..utils import log


def _np_weighted_percentile(values: np.ndarray, weights: Optional[np.ndarray],
                            alpha: float) -> float:
    """PercentileFun / WeightedPercentileFun, faithful to the reference
    (regression_objective.hpp:18-88). Two quirks of that code are
    mirrored deliberately rather than "fixed": the unweighted rule
    selects DESCENDING at float_pos = (1-alpha)*cnt via ArgMaxAtK
    (so the even-count median of [1,2,3,4] is 3, not 2.5), and the
    weighted rule interpolates only when the next item's cumulative-
    weight step is >= 1.0 — with threshold < cdf[pos], i.e. a negative
    interpolation factor, exactly as the reference computes it."""
    n = len(values)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(values[0])
    if weights is None:
        float_pos = (1.0 - alpha) * n
        pos = int(float_pos)
        if pos < 1:
            return float(np.max(values))
        if pos >= n:
            return float(np.min(values))
        bias = float_pos - pos
        d = np.sort(values)[::-1]            # descending, like ArgMaxAtK
        return float(d[pos - 1] - (d[pos - 1] - d[pos]) * bias)
    order = np.argsort(values, kind="stable")
    sv = values[order]
    cdf = np.cumsum(weights[order].astype(np.float64))
    threshold = alpha * cdf[-1]
    pos = int(np.searchsorted(cdf, threshold, side="right"))  # upper_bound
    pos = min(pos, n - 1)
    if pos == 0 or pos == n - 1:
        return float(sv[pos])
    v1, v2 = float(sv[pos - 1]), float(sv[pos])
    if cdf[pos + 1] - cdf[pos] >= 1.0:
        return (threshold - cdf[pos]) / (cdf[pos + 1] - cdf[pos]) \
            * (v2 - v1) + v1
    return float(v2)


class ObjectiveFunction:
    name = "custom"
    num_tree_per_iteration = 1
    is_constant_hessian = False
    is_renew_tree_output = False
    need_group = False

    def __init__(self, config: Config) -> None:
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = None if metadata.label is None else \
            np.asarray(metadata.label, dtype=np.float32)
        self.weights = None if metadata.weights is None else \
            np.asarray(metadata.weights, dtype=np.float32)
        self._label_dev = None if self.label is None else jnp.asarray(self.label)
        self._weights_dev = None if self.weights is None else jnp.asarray(self.weights)

    # -- helpers -------------------------------------------------------
    def _apply_weights(self, grad, hess):
        if self._weights_dev is not None:
            return grad * self._weights_dev, hess * self._weights_dev
        return grad, hess

    def get_gradients(self, score):
        raise NotImplementedError

    # -- persistent fused-loop hooks (treelearner/fused.py) ------------
    # Pointwise objectives can run gradients INSIDE the single-dispatch
    # training iteration, where rows live in leaf-permuted lane order.
    # ``persistent_aux`` returns (label_plane, weight_plane_or_None):
    # per-row constants that travel through the partition alongside the
    # score; ``persistent_grads(score, label, weight)`` must be a pure
    # jittable mirror of get_gradients over those planes. None = not
    # supported (ranking and renew-output objectives).
    def persistent_aux(self):
        return None

    def persistent_grads(self, score, label, weight):
        raise NotImplementedError

    def persistent_renew_spec(self):
        """(alpha, weighted) for the in-program leaf refit of
        renew-tree-output objectives (treelearner/fused.py
        _renew_leaf_outputs), or None when the objective has no leaf
        renewal. ``weighted`` must match whether ``persistent_aux``
        carries a weight plane — the refit reads it as the percentile
        weights (reference regression_objective.hpp RenewTreeOutput)."""
        return None

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    def convert_output(self, raw):
        return raw

    def renew_tree_output(self, pred_leaf: np.ndarray, residuals: np.ndarray,
                          num_leaves: int) -> Optional[np.ndarray]:
        return None

    def to_string(self) -> str:
        return self.name


# ---------------------------------------------------------------------------
# regression family (reference regression_objective.hpp)
# ---------------------------------------------------------------------------

class RegressionL2(ObjectiveFunction):
    name = "regression"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.sqrt = config.reg_sqrt

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt and self.label is not None:
            self.label = np.sign(self.label) * np.sqrt(np.abs(self.label))
            self._label_dev = jnp.asarray(self.label)
        self.is_constant_hessian = self.weights is None

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        g = score.astype(jnp.float32) - self._label_dev
        h = jnp.ones_like(g)
        return self._apply_weights(g, h)

    def persistent_aux(self):
        return self._label_dev, self._weights_dev

    def persistent_grads(self, score, label, weight):
        g = score - label
        h = jnp.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self, class_id):
        if self.weights is not None:
            return float(np.sum(self.label * self.weights) / np.sum(self.weights))
        return float(np.mean(self.label))

    def convert_output(self, raw):
        if self.sqrt:
            return jnp.sign(raw) * raw * raw
        return raw

    def to_string(self):
        return self.name + (" sqrt" if self.sqrt else "")


class RegressionL1(RegressionL2):
    name = "regression_l1"
    is_renew_tree_output = True

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        diff = score.astype(jnp.float32) - self._label_dev
        g = jnp.sign(diff)
        h = jnp.ones_like(g)
        return self._apply_weights(g, h)

    def persistent_grads(self, score, label, weight):
        g = jnp.sign(score - label)
        h = jnp.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def persistent_renew_spec(self):
        return 0.5, getattr(self, "weights", None) is not None

    def boost_from_score(self, class_id):
        return _np_weighted_percentile(self.label, self.weights, 0.5)

    def renew_tree_output(self, pred_leaf, residuals, num_leaves):
        """Median of residuals per leaf (reference
        RegressionL1loss::RenewTreeOutput, regression_objective.hpp:249)."""
        out = np.zeros(num_leaves)
        for leaf in range(num_leaves):
            m = pred_leaf == leaf
            w = None if self.weights is None else self.weights[m]
            out[leaf] = _np_weighted_percentile(residuals[m], w, 0.5)
        return out


class RegressionHuber(RegressionL2):
    name = "huber"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.alpha = config.alpha
        if self.alpha <= 0:
            log.fatal("alpha should be greater than 0 in huber")

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        diff = score.astype(jnp.float32) - self._label_dev
        g = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                      jnp.sign(diff) * self.alpha)
        h = jnp.ones_like(g)
        return self._apply_weights(g, h)

    def persistent_grads(self, score, label, weight):
        diff = score - label
        g = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                      jnp.sign(diff) * self.alpha)
        h = jnp.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h


class RegressionFair(RegressionL2):
    name = "fair"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.c = config.fair_c

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        x = score.astype(jnp.float32) - self._label_dev
        c = self.c
        g = c * x / (jnp.abs(x) + c)
        h = c * c / (jnp.abs(x) + c) ** 2
        return self._apply_weights(g, h)

    def persistent_grads(self, score, label, weight):
        x = score - label
        c = self.c
        g = c * x / (jnp.abs(x) + c)
        h = c * c / (jnp.abs(x) + c) ** 2
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self, class_id):
        return 0.0


class RegressionPoisson(RegressionL2):
    name = "poisson"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.max_delta_step = config.poisson_max_delta_step

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.is_constant_hessian = False
        if self.label is not None and np.any(self.label < 0):
            log.fatal("[poisson]: at least one target label is negative")

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        s = score.astype(jnp.float32)
        g = jnp.exp(s) - self._label_dev
        h = jnp.exp(s + self.max_delta_step)
        return self._apply_weights(g, h)

    def persistent_grads(self, score, label, weight):
        g = jnp.exp(score) - label
        h = jnp.exp(score + self.max_delta_step)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self, class_id):
        mean = RegressionL2.boost_from_score(self, class_id)
        return float(np.log(max(mean, 1e-20)))

    def convert_output(self, raw):
        return jnp.exp(raw)


class RegressionQuantile(RegressionL2):
    name = "quantile"
    is_renew_tree_output = True

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.alpha = config.alpha
        if not (0.0 < self.alpha < 1.0):
            log.fatal("alpha should be in (0, 1) for quantile")

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        delta = score.astype(jnp.float32) - self._label_dev
        g = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        h = jnp.ones_like(g)
        return self._apply_weights(g, h)

    def persistent_grads(self, score, label, weight):
        delta = score - label
        g = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        h = jnp.ones_like(g)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def persistent_renew_spec(self):
        return self.alpha, getattr(self, "weights", None) is not None

    def boost_from_score(self, class_id):
        return _np_weighted_percentile(self.label, self.weights, self.alpha)

    def renew_tree_output(self, pred_leaf, residuals, num_leaves):
        out = np.zeros(num_leaves)
        for leaf in range(num_leaves):
            m = pred_leaf == leaf
            w = None if self.weights is None else self.weights[m]
            out[leaf] = _np_weighted_percentile(residuals[m], w, self.alpha)
        return out


class RegressionMAPE(RegressionL1):
    name = "mape"
    is_renew_tree_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lw = 1.0 / np.maximum(1.0, np.abs(self.label))
        if self.weights is not None:
            lw = lw * self.weights
        self.label_weight = lw.astype(np.float32)
        self._label_weight_dev = jnp.asarray(self.label_weight)
        self.is_constant_hessian = self.weights is None

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        diff = score.astype(jnp.float32) - self._label_dev
        g = jnp.sign(diff) * self._label_weight_dev
        h = jnp.ones_like(g) if self._weights_dev is None else self._weights_dev
        return g, h

    def persistent_aux(self):
        # the weight plane carries label_weight = w / max(1, |label|):
        # it is both the gradient scale and the renewal percentile
        # weight (reference RegressionMAPELOSS::RenewTreeOutput)
        return self._label_dev, self._label_weight_dev

    def persistent_grads(self, score, label, weight):
        g = jnp.sign(score - label) * weight
        # sample weight = label_weight * max(1, |label|)
        h = weight * jnp.maximum(1.0, jnp.abs(label))
        return g, h

    def persistent_renew_spec(self):
        return 0.5, True

    def boost_from_score(self, class_id):
        return _np_weighted_percentile(self.label, self.label_weight, 0.5)

    def renew_tree_output(self, pred_leaf, residuals, num_leaves):
        out = np.zeros(num_leaves)
        for leaf in range(num_leaves):
            m = pred_leaf == leaf
            out[leaf] = _np_weighted_percentile(residuals[m],
                                                self.label_weight[m], 0.5)
        return out


class RegressionGamma(RegressionPoisson):
    name = "gamma"

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        s = score.astype(jnp.float32)
        g = 1.0 - self._label_dev / jnp.exp(s)
        h = self._label_dev / jnp.exp(s)
        return self._apply_weights(g, h)

    def persistent_grads(self, score, label, weight):
        g = 1.0 - label / jnp.exp(score)
        h = label / jnp.exp(score)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h


class RegressionTweedie(RegressionPoisson):
    name = "tweedie"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.rho = config.tweedie_variance_power

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        s = score.astype(jnp.float32)
        y = self._label_dev
        rho = self.rho
        g = -y * jnp.exp((1 - rho) * s) + jnp.exp((2 - rho) * s)
        h = (-y * (1 - rho) * jnp.exp((1 - rho) * s)
             + (2 - rho) * jnp.exp((2 - rho) * s))
        return self._apply_weights(g, h)

    def persistent_grads(self, score, label, weight):
        rho = self.rho
        g = -label * jnp.exp((1 - rho) * score) + jnp.exp((2 - rho) * score)
        h = (-label * (1 - rho) * jnp.exp((1 - rho) * score)
             + (2 - rho) * jnp.exp((2 - rho) * score))
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h


# ---------------------------------------------------------------------------
# binary (reference binary_objective.hpp:21)
# ---------------------------------------------------------------------------

class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config: Config, is_pos: Optional[Callable] = None) -> None:
        super().__init__(config)
        self.sigmoid = config.sigmoid
        if self.sigmoid <= 0:
            log.fatal("Sigmoid parameter %f should be greater than zero",
                      self.sigmoid)
        self.is_unbalance = config.is_unbalance
        self.scale_pos_weight = config.scale_pos_weight
        self._is_pos = is_pos or (lambda y: y > 0)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        is_pos = self._is_pos(self.label)
        cnt_pos = int(np.sum(is_pos))
        cnt_neg = num_data - cnt_pos
        if cnt_pos == 0 or cnt_neg == 0:
            log.warning("Contains only one class")
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self._sign = jnp.asarray(np.where(is_pos, 1.0, -1.0).astype(np.float32))
        self._lw = jnp.asarray(np.where(is_pos, w_pos, w_neg).astype(np.float32))
        self._cnt_pos, self._cnt_neg = cnt_pos, cnt_neg
        self.is_constant_hessian = False

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        s = score.astype(jnp.float32)
        response = -self._sign * self.sigmoid / \
            (1.0 + jnp.exp(self._sign * self.sigmoid * s))
        abs_resp = jnp.abs(response)
        g = response * self._lw
        h = abs_resp * (self.sigmoid - abs_resp) * self._lw
        return self._apply_weights(g, h)

    def persistent_aux(self):
        # one aux plane: signed per-row weight sign*lw*w (sign in {+-1},
        # lw*w > 0) — recovered as sign() / abs() in persistent_grads
        aux = self._sign * self._lw
        if self._weights_dev is not None:
            aux = aux * self._weights_dev
        return aux, None

    def persistent_grads(self, score, label, weight):
        sign = jnp.sign(label)
        lw = jnp.abs(label)
        response = -sign * self.sigmoid / \
            (1.0 + jnp.exp(sign * self.sigmoid * score))
        abs_resp = jnp.abs(response)
        g = response * lw
        h = abs_resp * (self.sigmoid - abs_resp) * lw
        return g, h

    def boost_from_score(self, class_id):
        if self.weights is not None:
            suml = float(np.sum(self._is_pos(self.label) * self.weights))
            sumw = float(np.sum(self.weights))
        else:
            suml = float(np.sum(self._is_pos(self.label)))
            sumw = float(self.num_data)
        pavg = min(max(suml / max(sumw, 1e-15), 1e-15), 1.0 - 1e-15)
        initscore = float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)
        log.info("[%s:BoostFromScore]: pavg=%f -> initscore=%f", self.name,
                 pavg, initscore)
        return initscore

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"{self.name} sigmoid:{self.sigmoid}"


# ---------------------------------------------------------------------------
# multiclass (reference multiclass_objective.hpp:24/:186)
# ---------------------------------------------------------------------------

class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.num_class = config.num_class
        self.num_tree_per_iteration = self.num_class
        self.factor = self.num_class / max(self.num_class - 1.0, 1.0)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = self.label.astype(np.int32)
        if np.any((lab < 0) | (lab >= self.num_class)):
            log.fatal("Label must be in [0, %d) for multiclass", self.num_class)
        self._onehot = jnp.asarray(
            (lab[None, :] == np.arange(self.num_class)[:, None]).astype(np.float32))
        self.factor = self.num_class / max(self.num_class - 1, 1)

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        """score: [num_class, N] raw scores; returns [num_class, N] each."""
        p = jax.nn.softmax(score.astype(jnp.float32), axis=0)
        g = p - self._onehot
        h = self.factor * p * (1.0 - p)
        if self._weights_dev is not None:
            g = g * self._weights_dev[None, :]
            h = h * self._weights_dev[None, :]
        return g, h

    def convert_output(self, raw):
        return jax.nn.softmax(raw, axis=-1)


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.num_class = config.num_class
        self.num_tree_per_iteration = self.num_class
        self.sigmoid = config.sigmoid
        self._binary: list = []

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._binary = []
        for k in range(self.num_class):
            b = BinaryLogloss(self.config,
                              is_pos=functools.partial(
                                  lambda y, kk: np.abs(y - kk) < 1e-9, kk=k))
            b.init(metadata, num_data)
            self._binary.append(b)

    def get_gradients(self, score):
        gs, hs = [], []
        for k in range(self.num_class):
            g, h = self._binary[k].get_gradients(score[k])
            gs.append(g)
            hs.append(h)
        return jnp.stack(gs), jnp.stack(hs)

    def boost_from_score(self, class_id):
        return self._binary[class_id].boost_from_score(0)

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))


# ---------------------------------------------------------------------------
# cross entropy (reference xentropy_objective.hpp)
# ---------------------------------------------------------------------------

class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any((self.label < 0) | (self.label > 1)):
            log.fatal("[%s]: label must be in [0, 1]", self.name)

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score.astype(jnp.float32)))
        g = z - self._label_dev
        h = z * (1.0 - z)
        return self._apply_weights(g, h)

    def persistent_aux(self):
        return self._label_dev, self._weights_dev

    def persistent_grads(self, score, label, weight):
        z = 1.0 / (1.0 + jnp.exp(-score))
        g = z - label
        h = z * (1.0 - z)
        if weight is not None:
            g, h = g * weight, h * weight
        return g, h

    def boost_from_score(self, class_id):
        if self.weights is not None:
            pavg = float(np.sum(self.label * self.weights) / np.sum(self.weights))
        else:
            pavg = float(np.mean(self.label))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return float(np.log(pavg / (1.0 - pavg)))

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-raw))


class CrossEntropyLambda(ObjectiveFunction):
    name = "cross_entropy_lambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any((self.label < 0) | (self.label > 1)):
            log.fatal("[%s]: label must be in [0, 1]", self.name)

    # tpulint: jit-ok(per-objective gradient kernel; static self, stable arity)
    @functools.partial(jax.jit, static_argnums=0)
    def get_gradients(self, score):
        """Reference xentropy_objective.hpp:185-213: unweighted variant
        equals plain cross-entropy; the weighted variant treats the score
        as a log-intensity with prob = 1-(1-z)^w."""
        s = score.astype(jnp.float32)
        if self._weights_dev is None:
            z = 1.0 / (1.0 + jnp.exp(-s))
            g = z - self._label_dev
            h = z * (1.0 - z)
            return g, h
        w = self._weights_dev
        y = self._label_dev
        epf = jnp.exp(s)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = 1.0 / epf
        g = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d = c - 1.0
        b = (c / (d * d)) * (1.0 + w * epf - c)
        h = a * (1.0 + y * b)
        return g, h

    def boost_from_score(self, class_id):
        havg = float(np.mean(self.label)) if self.weights is None else \
            float(np.sum(self.label * self.weights) / np.sum(self.weights))
        initscore = float(np.log(max(np.exp(havg) - 1.0, 1e-15)))
        log.info("[%s:BoostFromScore]: havg=%f -> initscore=%f", self.name,
                 havg, initscore)
        return initscore

    def convert_output(self, raw):
        return jnp.log1p(jnp.exp(raw))


# ---------------------------------------------------------------------------
# factory (reference objective_function.cpp:15)
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, type] = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """CreateObjectiveFunction; returns None for objective=custom (the
    caller must then supply gradients, reference
    objective_function.cpp:49-51)."""
    name = config.objective
    if name == "custom":
        return None
    if name in ("lambdarank", "rank_xendcg"):
        from .rank import LambdarankNDCG, RankXENDCG
        return (LambdarankNDCG if name == "lambdarank" else RankXENDCG)(config)
    cls = _REGISTRY.get(name)
    if cls is None:
        log.fatal("Unknown objective type name: %s", name)
    return cls(config)
