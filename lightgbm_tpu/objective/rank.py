"""Ranking objectives: LambdaRank-NDCG and XE-NDCG.

TPU re-design of the reference per-query scalar loops
(reference: src/objective/rank_objective.hpp — base RankingObjective
:27-96 iterating GetGradientsForOneQuery per query; LambdarankNDCG
:98-286 with pairwise ΔNDCG-weighted lambdas; RankXENDCG :288-360).

Instead of an OpenMP loop over queries with per-pair scalar math, the
queries are bucketed by padded size (powers of two) and each bucket is
evaluated as one batched [Q_bucket, M, M] masked pairwise program —
embarrassingly parallel on the VPU. The reference's 1M-entry sigmoid
lookup table (ConstructSigmoidTable :245-258) is unnecessary on TPU:
transcendentals are vectorized hardware ops.

The truncation level enters only through CalMaxDCGAtK
(rank_objective.hpp:127-129), matching the reference.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config
from ..utils import log
from .functions import ObjectiveFunction

K_MAX_POSITION = 10000


def default_label_gain(max_label: int = 31) -> np.ndarray:
    """2^i - 1 gains (reference DCGCalculator::DefaultLabelGain)."""
    return (np.power(2.0, np.arange(max_label)) - 1.0)


class DCGCalculator:
    """reference include/LightGBM/metric.h:63 + src/metric/dcg_calculator.cpp."""

    def __init__(self, label_gain: Optional[List[float]] = None) -> None:
        if label_gain:
            self.label_gain = np.asarray(label_gain, dtype=np.float64)
        else:
            self.label_gain = default_label_gain()
        self.discount = 1.0 / np.log2(np.arange(K_MAX_POSITION) + 2.0)

    def cal_max_dcg_at_k(self, k: int, labels: np.ndarray) -> float:
        labels = np.asarray(labels)
        srt = np.sort(labels)[::-1]
        k = min(k, len(srt))
        gains = self.label_gain[srt[:k].astype(np.int64)]
        return float(np.sum(gains * self.discount[:k]))

    def cal_dcg_at_k(self, k: int, labels: np.ndarray, scores: np.ndarray) -> float:
        order = np.argsort(-scores, kind="stable")
        k = min(k, len(labels))
        lab = np.asarray(labels)[order[:k]].astype(np.int64)
        return float(np.sum(self.label_gain[lab] * self.discount[:k]))

    def check_label(self, labels: np.ndarray) -> None:
        if np.any(labels < 0) or np.any(labels >= len(self.label_gain)):
            log.fatal("Label excel(%d) in ranking cannot be handled; "
                      "set label_gain", int(np.max(labels)))


def _bucket_queries(boundaries: np.ndarray, min_size: int = 8,
                    max_rows_per_chunk: int = 1 << 22):
    """Group queries into padded-size buckets; big buckets are further
    chunked so the [Q, M, M] pairwise tensor stays bounded."""
    sizes = np.diff(boundaries)
    buckets: Dict[int, List[int]] = {}
    for qi, sz in enumerate(sizes):
        m = min_size
        while m < sz:
            m *= 2
        buckets.setdefault(m, []).append(qi)
    chunks = []
    for m, qids in sorted(buckets.items()):
        per_chunk = max(1, max_rows_per_chunk // (m * m))
        for i in range(0, len(qids), per_chunk):
            chunks.append((m, qids[i:i + per_chunk]))
    return chunks


class RankingObjective(ObjectiveFunction):
    need_group = True

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.seed = config.objective_seed

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Ranking tasks require query information")
        self.boundaries = np.asarray(metadata.query_boundaries, dtype=np.int64)
        self.num_queries = len(self.boundaries) - 1
        self._chunks = _bucket_queries(self.boundaries)
        # padded index matrices per chunk (host-built once)
        self._chunk_idx = []
        for m, qids in self._chunks:
            idx = np.zeros((len(qids), m), dtype=np.int32)
            valid = np.zeros((len(qids), m), dtype=bool)
            for r, q in enumerate(qids):
                b, e = self.boundaries[q], self.boundaries[q + 1]
                idx[r, :e - b] = np.arange(b, e)
                valid[r, :e - b] = True
            self._chunk_idx.append((jnp.asarray(idx), jnp.asarray(valid),
                                    np.asarray(qids)))


class LambdarankNDCG(RankingObjective):
    name = "lambdarank"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self.norm = config.lambdarank_norm
        self.truncation_level = config.lambdarank_truncation_level
        self.dcg = DCGCalculator(config.label_gain)
        if self.sigmoid <= 0:
            log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.dcg.check_label(self.label)
        inv = np.zeros(self.num_queries)
        for q in range(self.num_queries):
            b, e = self.boundaries[q], self.boundaries[q + 1]
            maxdcg = self.dcg.cal_max_dcg_at_k(self.truncation_level,
                                               self.label[b:e])
            inv[q] = 1.0 / maxdcg if maxdcg > 0 else 0.0
        self.inverse_max_dcgs = inv
        self._gain_dev = jnp.asarray(self.dcg.label_gain, jnp.float32)
        self._disc_dev = None  # built per bucket size

    # tpulint: jit-ok(rank lambda kernel; static self, stable bucket shapes)
    @functools.partial(jax.jit, static_argnums=(0,))
    def _chunk_lambdas(self, score, idx, valid, inv_max_dcg):
        """One padded bucket: [Q, M] gathered scores/labels → lambdas."""
        q, m = idx.shape
        s = jnp.where(valid, score[idx].astype(jnp.float32), -jnp.inf)
        lab = jnp.where(valid, self._label_dev[idx], -1.0)
        order = jnp.argsort(-s, axis=1, stable=True)
        s_s = jnp.take_along_axis(s, order, 1)
        lab_s = jnp.take_along_axis(lab, order, 1).astype(jnp.int32)
        val_s = jnp.take_along_axis(valid, order, 1)
        cnt = valid.sum(axis=1)
        disc = 1.0 / jnp.log2(jnp.arange(m, dtype=jnp.float32) + 2.0)
        gain = self._gain_dev[jnp.maximum(lab_s, 0)]

        best = s_s[:, 0]
        worst = jnp.take_along_axis(
            s_s, jnp.maximum(cnt - 1, 0)[:, None], 1)[:, 0]

        hi_l = lab_s[:, :, None]
        lo_l = lab_s[:, None, :]
        pair_ok = (hi_l > lo_l) & val_s[:, :, None] & val_s[:, None, :]
        ds = s_s[:, :, None] - s_s[:, None, :]
        dcg_gap = gain[:, :, None] - gain[:, None, :]
        paired_disc = jnp.abs(disc[None, :, None] - disc[None, None, :])
        delta_ndcg = dcg_gap * paired_disc * inv_max_dcg[:, None, None]
        if self.norm:
            scale = jnp.where((best != worst)[:, None, None],
                              1.0 / (0.01 + jnp.abs(ds)), 1.0)
            delta_ndcg = delta_ndcg * scale
        p0 = 1.0 / (1.0 + jnp.exp(ds * self.sigmoid))
        p_lambda = jnp.where(pair_ok, -self.sigmoid * delta_ndcg * p0, 0.0)
        p_hess = jnp.where(pair_ok,
                           p0 * (1.0 - p0) * self.sigmoid ** 2 * delta_ndcg, 0.0)
        lam_s = p_lambda.sum(axis=2) - p_lambda.sum(axis=1)
        hes_s = p_hess.sum(axis=2) + p_hess.sum(axis=1)
        sum_lambdas = -2.0 * p_lambda.sum(axis=(1, 2))
        if self.norm:
            nf = jnp.where(sum_lambdas > 0,
                           jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, 1e-20),
                           1.0)
            lam_s = lam_s * nf[:, None]
            hes_s = hes_s * nf[:, None]
        # unsort back to query order
        lam = jnp.zeros_like(lam_s).at[jnp.arange(q)[:, None], order].set(lam_s)
        hes = jnp.zeros_like(hes_s).at[jnp.arange(q)[:, None], order].set(hes_s)
        return lam, hes

    def get_gradients(self, score):
        n = self.num_data
        grad = jnp.zeros(n, jnp.float32)
        hess = jnp.zeros(n, jnp.float32)
        for (m, qids), (idx, valid, qarr) in zip(self._chunks, self._chunk_idx):
            inv = jnp.asarray(self.inverse_max_dcgs[qarr], jnp.float32)
            lam, hes = self._chunk_lambdas(score, idx, valid, inv)
            grad = grad.at[idx].add(jnp.where(valid, lam, 0.0))
            hess = hess.at[idx].add(jnp.where(valid, hes, 0.0))
        return grad, hess


class RankXENDCG(RankingObjective):
    name = "rank_xendcg"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._rng = np.random.RandomState(self.seed)

    # tpulint: jit-ok(rank lambda kernel; static self, stable bucket shapes)
    @functools.partial(jax.jit, static_argnums=(0,))
    def _chunk_lambdas(self, score, idx, valid, rands):
        """reference RankXENDCG::GetGradientsForOneQuery
        (rank_objective.hpp:304-357): third-order XE-NDCG approximation."""
        s = jnp.where(valid, score[idx].astype(jnp.float32), -jnp.inf)
        lab = jnp.where(valid, self._label_dev[idx], 0.0)
        cnt = valid.sum(axis=1)
        rho = jax.nn.softmax(s, axis=1)
        rho = jnp.where(valid, rho, 0.0)
        phi = jnp.where(valid, jnp.exp2(jnp.floor(lab)) - rands, 0.0)
        inv_den = 1.0 / jnp.maximum(phi.sum(axis=1, keepdims=True), 1e-15)
        term1 = -phi * inv_den + rho
        params = jnp.where(valid, term1 / jnp.maximum(1.0 - rho, 1e-15), 0.0)
        sum_l1 = params.sum(axis=1, keepdims=True)
        term2 = rho * (sum_l1 - params)
        lam = term1 + term2
        params2 = jnp.where(valid, term2 / jnp.maximum(1.0 - rho, 1e-15), 0.0)
        sum_l2 = params2.sum(axis=1, keepdims=True)
        lam = lam + rho * (sum_l2 - params2)
        hes = rho * (1.0 - rho)
        small = (cnt <= 1)[:, None]
        lam = jnp.where(small | ~valid, 0.0, lam)
        hes = jnp.where(small | ~valid, 0.0, hes)
        return lam, hes

    def get_gradients(self, score):
        n = self.num_data
        grad = jnp.zeros(n, jnp.float32)
        hess = jnp.zeros(n, jnp.float32)
        for (m, qids), (idx, valid, qarr) in zip(self._chunks, self._chunk_idx):
            rands = jnp.asarray(
                self._rng.rand(idx.shape[0], idx.shape[1]).astype(np.float32))
            lam, hes = self._chunk_lambdas(score, idx, valid, rands)
            grad = grad.at[idx].add(jnp.where(valid, lam, 0.0))
            hess = hess.at[idx].add(jnp.where(valid, hes, 0.0))
        return grad, hess
